"""Fused Pallas TPU kernel for the lingru linear recurrence — fwd + bwd.

The ``kind="lingru"`` layer (models/lingru.py) hoists all arithmetic
density into one [B*T, in] x [in, 4H] MXU matmul and leaves a purely
elementwise affine recurrence ``h_t = a_t*h_{t-1} + b_t`` to
``lax.associative_scan``. XLA's generic scan materialises every
log-depth round trip through HBM: ~2*log2(T) full [2,B,T,H] tensors
read AND written per layer. This module fuses the whole tail — the
sigmoid/tanh gate math, the log-depth scan, and the ``(1-z)*h + z*c``
recombination — into ONE VMEM-resident Pallas launch per layer, with
both directions of the bidirectional stack solved in the same pass
(the lingru trick of stacking the time-reversed backward direction as
extra batch rows, models/lingru.py ``bidir_lingru_layer``).

Design (mirrors the proven ``pallas_gru.py`` v3 shape):

- **Time-only serial grid.** The TPU walks the grid sequentially, so
  the affine carry ``(h at the block boundary)`` lives in f32 VMEM
  scratch across grid steps. All ``S*B`` direction-stacked rows stay
  resident; time is the only grid axis, blocked to fit VMEM with the
  next block's DMA double-buffered behind the current block's compute.
- **In-block log-depth scan.** Each time block runs a Hillis-Steele
  inclusive scan over the affine pairs ``(a, b)`` — a static Python
  loop of ceil(log2(t_blk)) masked-shift rounds, entirely in VMEM —
  then applies the composed maps to the carried boundary state:
  ``h = A*carry + B``. Serial depth per layer is nt + log2(t_blk)
  elementwise rounds with zero HBM traffic in between.
- **Gates recomputed in the backward** (the kernel analogue of
  ``ModelConfig.remat_scan``): the custom VJP stores only the layer
  inputs/outputs the caller keeps anyway (the gate projections ``p``
  and hidden states ``h``) and recomputes z/c in-kernel. The upstream
  recurrence ``g_t = dy_t + a_{t+1}*g_{t+1}`` is rewritten through
  ``e_t = a_t*g_t`` as the suffix affine scan
  ``e_t = a_t*e_{t+1} + a_t*dy_t`` — coefficients indexed WITHIN each
  step, so the same log-depth machinery runs time-reversed with a
  clean e-carry across blocks, and ``g_t = dy_t + e_{t+1}`` falls out
  by a one-row shift. ``h_{t-1}`` at block boundaries streams in as a
  strided slice of the stored states (one row per block, the
  ``pallas_gru`` boundary-row idiom).
- **The projection matmul stays outside.** ``x @ W4 + b4`` (and its
  dW4/dx/db4 grads) remain plain XLA GEMMs; the custom VJP covers only
  the scan tail, which is exactly the part XLA schedules badly.

Numerics: gates and scan always accumulate in float32 regardless of
the input dtype; outputs cast back. ``interpret=True`` runs the same
kernels on CPU — tier-1 pins fwd AND grad parity against
``linear_scan_ref``/``bidir_lingru_stack`` at 1e-5 without a TPU.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from roko_tpu.models.layers import dropout as _dropout, weight as _weight

# VMEM working-set budget per kernel invocation (double-buffered blocks
# included) — same figure pallas_gru uses; the guide says ~16 MB/core.
_VMEM_BUDGET = 12 * 1024 * 1024


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _pick_tblk(T: int, rows: int, hidden: int, itemsize: int, bwd: bool) -> int:
    """Largest divisor-of-T time block whose working set fits VMEM.

    Streamed per (time, row): fwd reads p[2H] and writes h[H]; bwd
    reads p[2H]+h[H]+dy[H] and writes dp[2H] (+1H boundary slack).
    The Hillis-Steele rounds keep ~4 extra f32 (t_blk, rows, H)
    temporaries alive (A, B and their shifted copies), and the f32
    carry scratch is resident across grid steps. t_blk=1 always
    "fits" — it degrades to a serial per-step recurrence, still one
    launch."""
    per_row = (7 if bwd else 3) * hidden * itemsize  # double-buffered streams
    scan_tmp = 4 * hidden * 4  # f32 scan temporaries per (time, row)
    resident = rows * hidden * 4 * (2 if bwd else 1)  # carry scratch
    for t_blk in (d for d in range(T, 0, -1) if T % d == 0):
        if t_blk * rows * (2 * per_row + scan_tmp) + resident <= _VMEM_BUDGET:
            return t_blk
    return 1


def _fwd_kernel(t_blk: int, hidden: int, out_dtype):
    """p block (t_blk, R, 2H) -> h block (t_blk, R, H); f32 carry (R, H)
    persists across the sequential time grid."""

    def kernel(p_ref, h_ref, carry):
        @pl.when(pl.program_id(0) == 0)
        def _init():
            carry[...] = jnp.zeros_like(carry)

        pf = p_ref[...].astype(jnp.float32)
        z = jax.nn.sigmoid(pf[..., :hidden])
        c = jnp.tanh(pf[..., hidden:])
        A = 1.0 - z
        B = z * c
        # Hillis-Steele inclusive scan over the block's leading time
        # axis: element t composes with element t-d under the affine
        # combine (A_l, B_l) o (A_r, B_r) = (A_l*A_r, B_l*A_r + B_r)
        # (left = earlier), identity-padded at the top.
        d = 1
        while d < t_blk:
            A_s = jnp.concatenate([jnp.ones_like(A[:d]), A[:-d]], axis=0)
            B_s = jnp.concatenate([jnp.zeros_like(B[:d]), B[:-d]], axis=0)
            A, B = A_s * A, B_s * A + B
            d *= 2
        h = A * carry[...][None] + B
        h_ref[...] = h.astype(out_dtype)
        carry[...] = h[-1]

    return kernel


def _bwd_kernel(t_blk: int, nt: int, hidden: int):
    """Reverse-time pass: grid step k visits time block nt-1-k.

    Computes dL/dp for the block from (p, h, dy) via the e-scan
    (module docstring); ``hb_ref`` carries the previous block's last
    hidden row (h_{t-1} across the block boundary), zeros at global
    t=0."""

    def kernel(p_ref, h_ref, dy_ref, hb_ref, dp_ref, ecarry):
        k = pl.program_id(0)

        @pl.when(k == 0)
        def _init():  # e_T = 0: g at the global last step is just dy
            ecarry[...] = jnp.zeros_like(ecarry)

        pf = p_ref[...].astype(jnp.float32)
        z = jax.nn.sigmoid(pf[..., :hidden])
        c = jnp.tanh(pf[..., hidden:])
        a = 1.0 - z
        dy = dy_ref[...].astype(jnp.float32)
        e_in = ecarry[...]  # e_{t+1} at this block's LAST index
        # suffix affine scan e_t = a_t*e_{t+1} + a_t*dy_t: element t
        # composes with element t+d — (A_l, B_l) o (A_r, B_r) =
        # (A_l*A_r, A_l*B_r + B_l), identity-padded at the bottom.
        A = a
        B = a * dy
        d = 1
        while d < t_blk:
            A_s = jnp.concatenate([A[d:], jnp.ones_like(A[:d])], axis=0)
            B_s = jnp.concatenate([B[d:], jnp.zeros_like(B[:d])], axis=0)
            A, B = A * A_s, A * B_s + B
            d *= 2
        e = A * e_in[None] + B
        ecarry[...] = e[0]  # e_{t+1} for the previous block's last row
        e_next = jnp.concatenate([e[1:], e_in[None]], axis=0)
        g = dy + e_next  # total grad into h_t
        hf = h_ref[...].astype(jnp.float32)
        # h_{t-1}: in-block shift + streamed boundary row (zeros at the
        # global first block, which the reverse grid visits LAST)
        not_first = jnp.where(k == nt - 1, 0.0, 1.0)
        h_prev0 = hb_ref[...].astype(jnp.float32) * not_first
        h_prev = jnp.concatenate([h_prev0, hf[:-1]], axis=0)
        da = g * h_prev  # h_t = a_t*h_{t-1} + b_t
        dz = g * c - da  # a = 1-z, b = z*c
        dc = g * z
        dpz = dz * (z * (1.0 - z))
        dpc = dc * (1.0 - c * c)
        dp_ref[...] = jnp.concatenate([dpz, dpc], axis=-1).astype(dp_ref.dtype)

    return kernel


def _run_fwd(p: jax.Array, interpret: bool) -> jax.Array:
    T, R, H2 = p.shape
    hidden = H2 // 2
    t_blk = _pick_tblk(T, R, hidden, p.dtype.itemsize, bwd=False)
    nt = T // t_blk
    return pl.pallas_call(
        _fwd_kernel(t_blk, hidden, p.dtype),
        grid=(nt,),
        in_specs=[
            pl.BlockSpec(
                (t_blk, R, H2), lambda k: (k, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (t_blk, R, hidden), lambda k: (k, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((T, R, hidden), p.dtype),
        scratch_shapes=[pltpu.VMEM((R, hidden), jnp.float32)],
        interpret=interpret,
    )(p)


def _run_bwd(
    p: jax.Array, h: jax.Array, dy: jax.Array, interpret: bool
) -> jax.Array:
    T, R, H2 = p.shape
    hidden = H2 // 2
    t_blk = _pick_tblk(T, R, hidden, p.dtype.itemsize, bwd=True)
    nt = T // t_blk
    # one boundary row per time block: h at each block's last index
    hb = h[t_blk - 1 :: t_blk]  # (nt, R, H)
    rev = lambda k: (nt - 1 - k, 0, 0)  # noqa: E731 — reverse time walk
    spec = lambda w: pl.BlockSpec(  # noqa: E731
        (t_blk, R, w), rev, memory_space=pltpu.VMEM
    )
    return pl.pallas_call(
        _bwd_kernel(t_blk, nt, hidden),
        grid=(nt,),
        in_specs=[
            spec(H2),  # p
            spec(hidden),  # h
            spec(hidden),  # dy
            pl.BlockSpec(
                (1, R, hidden),
                lambda k: (jnp.maximum(nt - 2 - k, 0), 0, 0),
                memory_space=pltpu.VMEM,
            ),  # boundary rows (unused at the global first block)
        ],
        out_specs=spec(H2),
        out_shape=jax.ShapeDtypeStruct((T, R, H2), p.dtype),
        scratch_shapes=[pltpu.VMEM((R, hidden), jnp.float32)],
        interpret=interpret,
    )(p, h, dy, hb)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def lingru_scan_pallas(static: Tuple[bool], p: jax.Array) -> jax.Array:
    """Fused gate + log-depth scan over stacked projections.

    ``p`` is time-major [T, R, 2H] — R direction-stacked (and
    row-padded) batch rows, last axis the raw (z, c) gate projections.
    Returns h [T, R, H]. ``static = (interpret,)``."""
    (interpret,) = static
    return _run_fwd(p, interpret)


def _scan_vjp_fwd(static, p):
    (interpret,) = static
    h = _run_fwd(p, interpret)
    return h, (p, h)


def _scan_vjp_bwd(static, res, dy):
    (interpret,) = static
    p, h = res
    return (_run_bwd(p, h, dy, interpret),)


lingru_scan_pallas.defvjp(_scan_vjp_fwd, _scan_vjp_bwd)


def bidir_lingru_layer_pallas(
    layer: Dict[str, Any], x: jax.Array, *, interpret: bool = False
) -> jax.Array:
    """Both directions of one lingru layer, [B,T,in] -> [B,T,2H] — the
    fused-kernel twin of ``lingru.bidir_lingru_layer``. Same one
    [B*T, in] x [in, 4H] projection matmul outside the kernel; the
    backward direction rides as time-reversed extra rows so ONE launch
    solves both recurrences."""
    w_zx_f = _weight(layer["fwd"]["w_zx"], x.dtype)
    hidden = w_zx_f.shape[1]
    w4 = jnp.concatenate(
        [
            w_zx_f, _weight(layer["fwd"]["w_cx"], x.dtype),
            _weight(layer["bwd"]["w_zx"], x.dtype),
            _weight(layer["bwd"]["w_cx"], x.dtype),
        ],
        axis=1,
    )
    b4 = jnp.concatenate(
        [
            layer["fwd"]["b_z"], layer["fwd"]["b_c"],
            layer["bwd"]["b_z"], layer["bwd"]["b_c"],
        ]
    )
    proj = x @ w4 + b4  # [B,T,4H]
    B, T = x.shape[0], x.shape[1]
    Bp = _round_up(max(B, 1), 8)  # f32 sublane tile
    p_f = proj[..., : 2 * hidden]
    p_b = jnp.flip(proj[..., 2 * hidden :], axis=1)

    def _pad(rows):  # zero rows scan to h=0 and drop at the slice below
        return jnp.pad(rows, ((0, Bp - B), (0, 0), (0, 0)))

    pstack = jnp.concatenate([_pad(p_f), _pad(p_b)], axis=0)  # (2Bp,T,2H)
    pstack = pstack.swapaxes(0, 1)  # time-major (T, 2Bp, 2H)
    hs = lingru_scan_pallas((bool(interpret),), pstack)  # (T, 2Bp, H)
    h_f = hs[:, :B].swapaxes(0, 1)
    h_b = jnp.flip(hs[:, Bp : Bp + B].swapaxes(0, 1), axis=1)
    return jnp.concatenate([h_f, h_b], axis=-1)  # [B,T,2H]


def bidir_lingru_stack_pallas(
    params: Tuple[Dict[str, Any], ...],
    x: jax.Array,
    *,
    dropout: float = 0.0,
    deterministic: bool = True,
    rng: jax.Array | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Stacked bidirectional lingru on the fused kernel, [B,T,in] ->
    [B,T,2H]. Inter-layer dropout stays outside the kernels, matching
    ``lingru.bidir_lingru_stack`` (and torch) placement."""
    num_layers = len(params)
    for i, layer in enumerate(params):
        x = bidir_lingru_layer_pallas(layer, x, interpret=interpret)
        if dropout > 0.0 and not deterministic and i < num_layers - 1:
            assert rng is not None
            rng, sub = jax.random.split(rng)
            x = _dropout(sub, x, dropout)
    return x
