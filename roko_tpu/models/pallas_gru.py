"""Fused Pallas TPU kernels for the GRU recurrence — forward AND backward.

The hot loop of the consensus model is 90 timesteps x 2 directions x 3
layers of GRU steps (SURVEY.md §7 "hard parts" (a); semantics anchor:
the reference's 3-layer bidirectional ``torch.nn.GRU``,
roko/rnn_model.py:40-41). The lax.scan path re-materialises the hidden
state through HBM every step; these kernels run the whole serial chain
inside Pallas programs with the hidden state pinned in VMEM scratch.

Design (v3 forward / v2 backward — single launch per layer,
train-capable):

- **Time-only serial grid (v3 forward).** The TPU walks a Pallas grid
  sequentially, so v2's ``(S, nb, nt)`` grid ran 2 directions x nb
  batch blocks as *serial passes* over the 90-step chain — measured at
  just 7% over the scan path (BASELINE.md "Measured vs model"), because
  serial step count, not FLOPs, binds this recurrence. v3 keeps ALL
  directions and batch rows resident and makes time the only grid
  axis: one 90-step chain per layer, with the per-direction matmul and
  gate blocks inside a step mutually independent so the scheduler can
  overlap direction 0's VPU gate math with direction 1's MXU matmul.
  Falls back to the v2 grid when S*B rows exceed the VMEM budget.
- **Directions fused into one launch.** Both directions of a layer run
  in one ``pallas_call``; the backward direction's inputs are
  time-reversed on the host side so the kernel always recurs forward
  in kernel time. One launch per layer instead of two (3 per forward
  instead of 6).
- **Time-blocked streaming.** The grid's innermost axis walks time
  blocks while the hidden state carries across iterations in VMEM
  scratch (the TPU grid is sequential, scratch persists). Pallas
  double-buffers the next time block's DMA behind the current block's
  compute, so VMEM holds only ``2 x t_blk`` slabs instead of all T —
  which is what lets the batch block widen to 128-256 rows and fill the
  128x128 MXU (the previous kernel's whole-T residency capped blocks at
  64 rows, half the MXU).
- **Input projection stays outside.** ``x @ W_ih + b_ih`` for all
  timesteps and both directions is one large MXU matmul XLA already
  schedules well (same hoisting as the scan path, models/gru.py:11-14).
- **Backward kernel** (``custom_vjp``): recomputes the gates from the
  stored per-step hidden states (no activation stash beyond the layer
  output the caller keeps anyway), accumulates ``dW_hh``/``db_hh`` in
  VMEM across batch/time blocks, and streams ``dx_proj`` out; the
  weight-gradient matmuls for ``W_ih`` happen outside as one big GEMM.
  This makes ``use_pallas=True`` train-capable (round-1 gap).

Numerics: the recurrence accumulates the hidden state in float32; in
bfloat16 compute mode the per-step matmul runs bf16 x bf16 -> f32 (the
MXU fast path) and stored states/outputs are bf16. ``interpret=True``
runs the same kernels on CPU for tests.
"""

from __future__ import annotations

import functools
from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from roko_tpu.models.layers import dropout as _dropout

# VMEM working-set budget per kernel invocation (double-buffered blocks
# included). The guide's figure is ~16 MB/core; stay under it.
_VMEM_BUDGET = 12 * 1024 * 1024


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _pick_blocks(T: int, B: int, hidden: int, itemsize: int, bwd: bool):
    """Choose (t_blk, b_blk): batch rows first (MXU fill), then the
    largest divisor-of-T time block that fits the VMEM budget.

    b_blk targets 256 rows (two full MXU row-tiles) but is shrunk to the
    evenest 16-row-aligned split so per-block padding never exceeds 15
    rows (up to 15*nb dead rows across a multi-block batch) — naively
    capping at 256 would recur up to 255 dead rows for batches just over
    a block multiple."""
    nb = -(-B // 256)
    b_blk = min(256, _round_up(-(-B // nb), 16))
    divisors = [d for d in range(T, 0, -1) if T % d == 0]
    # bytes per (time, batch-row): fwd streams x_proj[3H] + out[H]; bwd
    # streams x_proj[3H] + h[H] + dy[H] + dx_proj[3H] + a 1-row h_prev
    # boundary block (counted as one extra H for slack).
    per_row = (9 if bwd else 4) * hidden * itemsize
    # 2x for double buffering
    for t_blk in divisors:
        if 2 * t_blk * b_blk * per_row <= _VMEM_BUDGET:
            return t_blk, b_blk
    return 1, b_blk


def _pick_tblk_v3(T: int, rows: int, hidden: int, itemsize: int,
                  n_dirs: int = 2, bwd: bool = False):
    """Largest divisor-of-T time block that fits the v3 (time-only
    grid) working set: double-buffered streams for ALL ``rows``
    (fwd: xp[3H]+out[H]; bwd: xp[3H]+h[H]+dy[H]+dxp[3H]+boundary slack)
    plus everything resident across grid steps — the f32 carry scratch,
    the per-direction whh/bhh weight blocks (pinned whole-kernel by
    their constant index maps), and in the backward the f32 dwhh/dbhh
    output blocks with ~2 extra in-flight copies for the fori_loop
    gradient-tuple carries (ADVICE r4: the old model omitted these and
    could overshoot real VMEM at hidden>=256). Returns None when even
    t_blk=1 does not fit — the caller then falls back to the
    batch-blocked v2 grid (correct everywhere, serialises batch
    blocks)."""
    per_row = (9 if bwd else 4) * hidden * itemsize
    wsize = n_dirs * (hidden + 1) * 3 * hidden  # whh [H,3H] + bhh [1,3H]
    resident = rows * hidden * 4 + wsize * itemsize
    if bwd:
        # dwhh/dbhh f32 outputs + ~2 carry copies alive during the loop
        # body (old tuple + updated tuple), and a second dh-sized carry
        resident += 3 * wsize * 4 + rows * hidden * 4
    for t_blk in (d for d in range(T, 0, -1) if T % d == 0):
        if 2 * t_blk * rows * per_row + resident <= _VMEM_BUDGET:
            # t_blk=1 is DMA-per-step but still one 90-step serial
            # chain — far ahead of v2's S x nb passes at wide batch
            return t_blk
    return None


def _fwd_kernel_v3(t_blk: int, Bp: int, S: int, hidden: int, cdt, out_dtype):
    """v3 forward: grid is TIME ONLY. Every direction and every batch
    row advances together in each sequential grid step, so a batch-512
    forward runs 90 serial steps instead of v2's 2 dirs x nb blocks x
    90 (the grid serialisation that left v2 within 7% of the scan path
    — BASELINE.md "Measured vs model"). The per-direction matmuls and
    gate blocks inside one step are data-independent, so the Mosaic
    scheduler can overlap direction 0's VPU gate math with direction
    1's MXU matmul — the overlap no grid ordering can express."""

    def kernel(xp_ref, whh_ref, bhh_ref, out_ref, h_scratch):
        @pl.when(pl.program_id(0) == 0)
        def _init():
            h_scratch[...] = jnp.zeros_like(h_scratch)

        def step(j, h):  # h: [S*Bp, H] float32
            xp = xp_ref[j].astype(jnp.float32)  # [S*Bp, 3H]
            outs = []
            for s in range(S):
                hs = h[s * Bp : (s + 1) * Bp]
                whh = whh_ref[s]  # [H, 3H]
                bhh = bhh_ref[s].astype(jnp.float32)  # [1, 3H]
                hp = (
                    jnp.dot(
                        hs.astype(cdt), whh,
                        preferred_element_type=jnp.float32,
                    )
                    + bhh
                )
                xps = xp[s * Bp : (s + 1) * Bp]
                r = jax.nn.sigmoid(xps[:, :hidden] + hp[:, :hidden])
                z = jax.nn.sigmoid(
                    xps[:, hidden : 2 * hidden] + hp[:, hidden : 2 * hidden]
                )
                n = jnp.tanh(xps[:, 2 * hidden :] + r * hp[:, 2 * hidden :])
                outs.append((1.0 - z) * n + z * hs)
            h_new = jnp.concatenate(outs, axis=0)
            out_ref[j] = h_new.astype(out_dtype)
            return h_new

        h_scratch[...] = lax.fori_loop(0, t_blk, step, h_scratch[...])

    return kernel


def _fwd_kernel(t_blk: int, hidden: int, cdt, out_dtype):
    def kernel(xp_ref, whh_ref, bhh_ref, out_ref, h_scratch):
        @pl.when(pl.program_id(2) == 0)
        def _init():
            h_scratch[...] = jnp.zeros_like(h_scratch)

        whh = whh_ref[0]  # [H, 3H]
        bhh = bhh_ref[0].astype(jnp.float32)  # [1, 3H], broadcasts

        def step(j, h):
            xp = xp_ref[j].astype(jnp.float32)  # [b_blk, 3H]
            hp = (
                jnp.dot(h.astype(cdt), whh, preferred_element_type=jnp.float32)
                + bhh
            )
            r = jax.nn.sigmoid(xp[:, :hidden] + hp[:, :hidden])
            z = jax.nn.sigmoid(
                xp[:, hidden : 2 * hidden] + hp[:, hidden : 2 * hidden]
            )
            n = jnp.tanh(xp[:, 2 * hidden :] + r * hp[:, 2 * hidden :])
            h_new = (1.0 - z) * n + z * h
            out_ref[j] = h_new.astype(out_dtype)
            return h_new

        h_scratch[...] = lax.fori_loop(0, t_blk, step, h_scratch[...])

    return kernel


def _bwd_kernel_v3(
    t_blk: int, nt: int, Bp: int, S: int, hidden: int, cdt, dxp_dtype
):
    """v3 backward: time-only reverse sweep with every direction and
    batch row resident (see _fwd_kernel_v3 for why the grid shape is
    the perf lever). dW_hh/db_hh accumulate in constant-index output
    blocks that stay resident across the whole grid; dh carries in
    scratch; per-direction blocks inside a step are independent, so the
    two directions' matmuls and gate math can overlap."""

    def kernel(
        xp_ref, h_ref, hprev_ref, dy_ref, whh_ref, bhh_ref,
        dxp_ref, dwhh_ref, dbhh_ref, dh_scratch,
    ):
        k = pl.program_id(0)

        @pl.when(k == 0)
        def _init():
            dh_scratch[...] = jnp.zeros_like(dh_scratch)
            dwhh_ref[...] = jnp.zeros(dwhh_ref.shape, dwhh_ref.dtype)
            dbhh_ref[...] = jnp.zeros(dbhh_ref.shape, dbhh_ref.dtype)

        first_time_block = k == nt - 1  # time blocks walked in reverse

        def step(jj, carry):
            # per-direction accumulators ride as TUPLES (S is static):
            # a stacked [S,H,3H] carry would need .at[s].add, which
            # lowers to scatter-add — unimplemented in Pallas TPU
            dh_all, dwhhs, dbhhs = carry
            dwhhs, dbhhs = list(dwhhs), list(dbhhs)
            j = t_blk - 1 - jj
            xp_row = xp_ref[j]
            h_row = h_ref[jnp.maximum(j - 1, 0)]
            hb_row = hprev_ref[0]
            dy_row = dy_ref[j]
            at_t0 = first_time_block & (j == 0)
            da_parts = []
            dh_parts = []
            for s in range(S):
                rows = slice(s * Bp, (s + 1) * Bp)
                whh = whh_ref[s]  # [H, 3H]
                bhh = bhh_ref[s].astype(jnp.float32)  # [1, 3H]
                xp = xp_row[rows].astype(jnp.float32)
                h_in_blk = h_row[rows].astype(jnp.float32)
                h_boundary = hb_row[rows].astype(jnp.float32)
                h_prev = jnp.where(
                    j > 0,
                    h_in_blk,
                    jnp.where(
                        at_t0, jnp.zeros_like(h_boundary), h_boundary
                    ),
                )
                hp = (
                    jnp.dot(
                        h_prev.astype(cdt), whh,
                        preferred_element_type=jnp.float32,
                    )
                    + bhh
                )
                r = jax.nn.sigmoid(xp[:, :hidden] + hp[:, :hidden])
                z = jax.nn.sigmoid(
                    xp[:, hidden : 2 * hidden] + hp[:, hidden : 2 * hidden]
                )
                hpn = hp[:, 2 * hidden :]
                n = jnp.tanh(xp[:, 2 * hidden :] + r * hpn)

                dh = dh_all[rows] + dy_row[rows].astype(jnp.float32)
                dz = dh * (h_prev - n) * z * (1.0 - z)
                dn_pre = dh * (1.0 - z) * (1.0 - n * n)
                dr_pre = dn_pre * hpn * r * (1.0 - r)
                da = jnp.concatenate([dr_pre, dz, dn_pre], axis=1)
                dhp = jnp.concatenate([dr_pre, dz, dn_pre * r], axis=1)
                da_parts.append(da.astype(dxp_dtype))
                dh_parts.append(
                    dh * z
                    + jnp.dot(
                        dhp.astype(cdt), whh.T,
                        preferred_element_type=jnp.float32,
                    )
                )
                dwhhs[s] = dwhhs[s] + jnp.dot(
                    h_prev.astype(cdt).T,
                    dhp.astype(cdt),
                    preferred_element_type=jnp.float32,
                )
                dbhhs[s] = dbhhs[s] + dhp.sum(axis=0, keepdims=True)
            dxp_ref[j] = jnp.concatenate(da_parts, axis=0)
            return (
                jnp.concatenate(dh_parts, axis=0),
                tuple(dwhhs),
                tuple(dbhhs),
            )

        dh0 = dh_scratch[...]
        dwhh0 = tuple(dwhh_ref[s] for s in range(S))
        dbhh0 = tuple(dbhh_ref[s] for s in range(S))
        dh, dwhhs, dbhhs = lax.fori_loop(0, t_blk, step, (dh0, dwhh0, dbhh0))
        dh_scratch[...] = dh
        for s in range(S):
            dwhh_ref[s] = dwhhs[s]
            dbhh_ref[s] = dbhhs[s]

    return kernel


def _bwd_kernel(t_blk: int, nt: int, hidden: int, cdt, dxp_dtype):
    """Reverse-time sweep: recompute gates from stored states, emit
    dx_proj, accumulate dW_hh/db_hh in VMEM output blocks (revisited
    across the inner grid axes), carry dh in scratch."""

    def kernel(
        xp_ref, h_ref, hprev_ref, dy_ref, whh_ref, bhh_ref,
        dxp_ref, dwhh_ref, dbhh_ref, dh_scratch,
    ):
        i, k = pl.program_id(1), pl.program_id(2)

        @pl.when(k == 0)
        def _init_dh():
            dh_scratch[...] = jnp.zeros_like(dh_scratch)

        @pl.when((i == 0) & (k == 0))
        def _init_acc():
            dwhh_ref[...] = jnp.zeros(dwhh_ref.shape, dwhh_ref.dtype)
            dbhh_ref[...] = jnp.zeros(dbhh_ref.shape, dbhh_ref.dtype)

        whh = whh_ref[0]  # [H, 3H]
        bhh = bhh_ref[0].astype(jnp.float32)  # [1, 3H], broadcasts
        first_time_block = k == nt - 1  # time blocks walked in reverse

        def step(jj, carry):
            dh, dwhh, dbhh = carry
            j = t_blk - 1 - jj
            xp = xp_ref[j].astype(jnp.float32)
            # h_{t-1}: previous row of this block, or the last row of
            # the previous time block, or zeros at t == 0
            h_in_blk = h_ref[jnp.maximum(j - 1, 0)].astype(jnp.float32)
            h_boundary = hprev_ref[0].astype(jnp.float32)
            at_t0 = first_time_block & (j == 0)
            h_prev = jnp.where(
                j > 0,
                h_in_blk,
                jnp.where(at_t0, jnp.zeros_like(h_boundary), h_boundary),
            )
            hp = (
                jnp.dot(
                    h_prev.astype(cdt), whh, preferred_element_type=jnp.float32
                )
                + bhh
            )
            r = jax.nn.sigmoid(xp[:, :hidden] + hp[:, :hidden])
            z = jax.nn.sigmoid(
                xp[:, hidden : 2 * hidden] + hp[:, hidden : 2 * hidden]
            )
            hpn = hp[:, 2 * hidden :]
            n = jnp.tanh(xp[:, 2 * hidden :] + r * hpn)

            dh = dh + dy_ref[j].astype(jnp.float32)
            dz = dh * (h_prev - n) * z * (1.0 - z)
            dn_pre = dh * (1.0 - z) * (1.0 - n * n)
            dr_pre = dn_pre * hpn * r * (1.0 - r)
            da = jnp.concatenate([dr_pre, dz, dn_pre], axis=1)  # dx_proj
            dhp = jnp.concatenate([dr_pre, dz, dn_pre * r], axis=1)
            dxp_ref[j] = da.astype(dxp_dtype)
            dh_next = dh * z + jnp.dot(
                dhp.astype(cdt), whh.T, preferred_element_type=jnp.float32
            )
            dwhh = dwhh + jnp.dot(
                h_prev.astype(cdt).T,
                dhp.astype(cdt),
                preferred_element_type=jnp.float32,
            )
            dbhh = dbhh + dhp.sum(axis=0, keepdims=True)
            return dh_next, dwhh, dbhh

        dh0 = dh_scratch[...]
        dwhh0 = dwhh_ref[0]
        dbhh0 = dbhh_ref[0]  # [1, 3H]
        dh, dwhh, dbhh = lax.fori_loop(0, t_blk, step, (dh0, dwhh0, dbhh0))
        dh_scratch[...] = dh
        dwhh_ref[0] = dwhh
        dbhh_ref[0] = dbhh

    return kernel


def _stack_dirs(
    arrs: Sequence[jax.Array], flags: Sequence[bool], Bp: int
) -> jax.Array:
    """[B,T,F] per direction -> time-major [T, S*Bp, F] with reversed
    directions flipped into kernel time and batch padded per direction."""
    B = arrs[0].shape[0]
    out = []
    for a, rev in zip(arrs, flags):
        if rev:
            a = jnp.flip(a, axis=1)
        if Bp != B:
            a = jnp.concatenate(
                [a, jnp.zeros((Bp - B,) + a.shape[1:], a.dtype)], axis=0
            )
        out.append(a.swapaxes(0, 1))  # [T, Bp, F]
    return jnp.concatenate(out, axis=1)  # [T, S*Bp, F]


def _unstack_dirs(
    stacked: jax.Array, flags: Sequence[bool], B: int, Bp: int
) -> Tuple[jax.Array, ...]:
    """Inverse of ``_stack_dirs``: [T, S*Bp, F] -> per-direction [B,T,F]."""
    out = []
    for s, rev in enumerate(flags):
        a = stacked[:, s * Bp : s * Bp + B].swapaxes(0, 1)  # [B,T,F]
        if rev:
            a = jnp.flip(a, axis=1)
        out.append(a)
    return tuple(out)


# static = (flags tuple, interpret, compute_dtype name)
@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _gru_multi(static, w_ih, b_ih, w_hh, b_hh, x):
    """S stacked GRU directions over shared input ``x`` [B,T,in].

    ``w_ih`` [S,in,3H], ``b_ih`` [S,3H], ``w_hh`` [S,H,3H], ``b_hh``
    [S,3H]; returns ``ys`` [S,B,T,H] in natural time order.
    """
    ys, _ = _gru_multi_fwd(static, w_ih, b_ih, w_hh, b_hh, x)
    return ys


def _xproj_stacked(static, w_ih, b_ih, x, Bp):
    flags, _, cdt_name = static
    S = len(flags)
    B, T, _ = x.shape
    H3 = w_ih.shape[2]
    cdt = jnp.dtype(cdt_name)
    # one [B*T, in] x [in, S*3H] MXU matmul for all directions
    w_cat = jnp.transpose(w_ih, (1, 0, 2)).reshape(w_ih.shape[1], S * H3)
    xp = x @ w_cat + b_ih.reshape(1, 1, S * H3)
    per_dir = [xp[..., s * H3 : (s + 1) * H3] for s in range(S)]
    return _stack_dirs(per_dir, flags, Bp).astype(cdt)  # [T, S*Bp, 3H]


def _gru_multi_fwd(static, w_ih, b_ih, w_hh, b_hh, x):
    flags, interpret, cdt_name = static
    S = len(flags)
    B, T, _ = x.shape
    hidden = w_hh.shape[1]
    cdt = jnp.dtype(cdt_name)

    # v3 when the whole S x B working set fits VMEM (the flagship
    # shapes do): time-only serial grid, see _fwd_kernel_v3. v2
    # batch-blocked grid otherwise.
    Bp16 = _round_up(B, 16)
    t3 = _pick_tblk_v3(T, S * Bp16, hidden, cdt.itemsize, n_dirs=S)
    if t3 is not None:
        Bp = Bp16
        xs = _xproj_stacked(static, w_ih, b_ih, x, Bp)
        R = S * Bp
        hs = pl.pallas_call(
            _fwd_kernel_v3(t3, Bp, S, hidden, cdt, cdt),
            grid=(T // t3,),
            out_shape=jax.ShapeDtypeStruct((T, R, hidden), cdt),
            in_specs=[
                pl.BlockSpec((t3, R, 3 * hidden), lambda k: (k, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((S, hidden, 3 * hidden), lambda k: (0, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((S, 1, 3 * hidden), lambda k: (0, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((t3, R, hidden), lambda k: (k, 0, 0),
                                   memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.VMEM((R, hidden), jnp.float32)],
            interpret=interpret,
        )(xs, w_hh.astype(cdt), b_hh.reshape(S, 1, 3 * hidden))
        per_dir = _unstack_dirs(hs, flags, B, Bp)
        ys = jnp.stack(per_dir, axis=0)  # [S,B,T,H]
        return ys, (w_ih, b_ih, w_hh, b_hh, x, ys)

    t_blk, b_blk = _pick_blocks(T, B, hidden, cdt.itemsize, bwd=False)
    Bp = _round_up(B, b_blk)
    nb, nt = Bp // b_blk, T // t_blk

    xs = _xproj_stacked(static, w_ih, b_ih, x, Bp)
    hs = pl.pallas_call(
        _fwd_kernel(t_blk, hidden, cdt, cdt),
        grid=(S, nb, nt),
        out_shape=jax.ShapeDtypeStruct((T, S * Bp, hidden), cdt),
        in_specs=[
            pl.BlockSpec((t_blk, b_blk, 3 * hidden),
                         lambda s, i, k: (k, s * nb + i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, hidden, 3 * hidden), lambda s, i, k: (s, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, 3 * hidden), lambda s, i, k: (s, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((t_blk, b_blk, hidden),
                               lambda s, i, k: (k, s * nb + i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((b_blk, hidden), jnp.float32)],
        interpret=interpret,
    )(xs, w_hh.astype(cdt), b_hh.reshape(S, 1, 3 * hidden))

    per_dir = _unstack_dirs(hs, flags, B, Bp)
    ys = jnp.stack(per_dir, axis=0)  # [S,B,T,H]
    return ys, (w_ih, b_ih, w_hh, b_hh, x, ys)


def _gru_multi_bwd(static, res, dys):
    flags, interpret, cdt_name = static
    w_ih, b_ih, w_hh, b_hh, x, ys = res
    S = len(flags)
    B, T, _ = x.shape
    hidden = w_hh.shape[1]
    cdt = jnp.dtype(cdt_name)

    # v3 when the whole S x B working set fits (same grid logic as the
    # forward: time is the only serial axis)
    Bp16 = _round_up(B, 16)
    t3 = _pick_tblk_v3(T, S * Bp16, hidden, cdt.itemsize, n_dirs=S, bwd=True)
    if t3 is not None:
        return _gru_multi_bwd_v3(static, res, dys, t3)

    t_blk, b_blk = _pick_blocks(T, B, hidden, cdt.itemsize, bwd=True)
    Bp = _round_up(B, b_blk)
    nb, nt = Bp // b_blk, T // t_blk

    xs, hs, dy, hs_bound = _bwd_prologue(
        static, w_ih, b_ih, x, ys, dys, Bp, t_blk, cdt
    )

    # time blocks are walked newest-first; hprev is the boundary row one
    # time block earlier (clamped at the start; the kernel masks t == 0)
    def tmap(s, i, k):
        return (nt - 1 - k, s * nb + i, 0)

    def tmap_prev(s, i, k):
        return (jnp.maximum(nt - 1 - k - 1, 0), s * nb + i, 0)

    dxp, dwhh, dbhh = pl.pallas_call(
        _bwd_kernel(t_blk, nt, hidden, cdt, cdt),
        grid=(S, nb, nt),
        out_shape=(
            jax.ShapeDtypeStruct((T, S * Bp, 3 * hidden), cdt),
            jax.ShapeDtypeStruct((S, hidden, 3 * hidden), jnp.float32),
            jax.ShapeDtypeStruct((S, 1, 3 * hidden), jnp.float32),
        ),
        in_specs=[
            pl.BlockSpec((t_blk, b_blk, 3 * hidden), tmap,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((t_blk, b_blk, hidden), tmap,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, b_blk, hidden), tmap_prev,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((t_blk, b_blk, hidden), tmap,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, hidden, 3 * hidden), lambda s, i, k: (s, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, 3 * hidden), lambda s, i, k: (s, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((t_blk, b_blk, 3 * hidden), tmap,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, hidden, 3 * hidden), lambda s, i, k: (s, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, 3 * hidden), lambda s, i, k: (s, 0, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[pltpu.VMEM((b_blk, hidden), jnp.float32)],
        interpret=interpret,
    )(xs, hs, hs_bound, dy, w_hh.astype(cdt), b_hh.reshape(S, 1, 3 * hidden))

    return _finish_bwd(
        flags, w_ih, b_ih, w_hh, b_hh, x, dxp, dwhh, dbhh, B, Bp, hidden
    )


def _bwd_prologue(static, w_ih, b_ih, x, ys, dys, Bp, t_blk, cdt):
    """Shared backward prologue: stacked x-projection, stored states and
    upstream grads in kernel-time layout, plus one boundary row per
    time block (h at the block's last step) — the kernel needs h_{t-1}
    across block edges but only ONE row of the previous block;
    streaming the whole block again would double the h-stream HBM
    traffic."""
    flags = static[0]
    xs = _xproj_stacked(static, w_ih, b_ih, x, Bp)
    hs = _stack_dirs(list(ys.astype(cdt)), flags, Bp)
    dy = _stack_dirs(list(dys.astype(cdt)), flags, Bp)
    hs_bound = hs[t_blk - 1 :: t_blk]  # [nt, S*Bp, H]
    return xs, hs, dy, hs_bound


def _finish_bwd(flags, w_ih, b_ih, w_hh, b_hh, x, dxp, dwhh, dbhh, B, Bp,
                hidden):
    """Shared backward epilogue: unstack dxp and run the big input-side
    GEMMs outside the kernel (dx, dW_ih, db_ih)."""
    S = len(flags)
    dbhh = dbhh.reshape(S, 3 * hidden)
    dxp_dirs = _unstack_dirs(dxp, flags, B, Bp)  # S x [B,T,3H]
    dxp_all = jnp.stack(dxp_dirs, axis=0).astype(jnp.float32)  # [S,B,T,3H]
    x32 = x.astype(jnp.float32)
    # dx = sum_s dxp_s @ w_ih_s^T ; dw_ih_s = x^T dxp_s — big MXU GEMMs
    dx = jnp.einsum("sbtn,sin->bti", dxp_all, w_ih.astype(jnp.float32))
    dw_ih = jnp.einsum("bti,sbtn->sin", x32, dxp_all)
    db_ih = dxp_all.sum(axis=(1, 2))
    return (
        dw_ih.astype(w_ih.dtype),
        db_ih.astype(b_ih.dtype),
        dwhh.astype(w_hh.dtype),
        dbhh.astype(b_hh.dtype),
        dx.astype(x.dtype),
    )


def _gru_multi_bwd_v3(static, res, dys, t3: int):
    flags, interpret, cdt_name = static
    w_ih, b_ih, w_hh, b_hh, x, ys = res
    S = len(flags)
    B, T, _ = x.shape
    hidden = w_hh.shape[1]
    cdt = jnp.dtype(cdt_name)
    Bp = _round_up(B, 16)
    R = S * Bp
    nt = T // t3

    xs, hs, dy, hs_bound = _bwd_prologue(
        static, w_ih, b_ih, x, ys, dys, Bp, t3, cdt
    )

    def tmap(k):
        return (nt - 1 - k, 0, 0)

    def tmap_prev(k):
        return (jnp.maximum(nt - 1 - k - 1, 0), 0, 0)

    const = lambda k: (0, 0, 0)  # noqa: E731

    dxp, dwhh, dbhh = pl.pallas_call(
        _bwd_kernel_v3(t3, nt, Bp, S, hidden, cdt, cdt),
        grid=(nt,),
        out_shape=(
            jax.ShapeDtypeStruct((T, R, 3 * hidden), cdt),
            jax.ShapeDtypeStruct((S, hidden, 3 * hidden), jnp.float32),
            jax.ShapeDtypeStruct((S, 1, 3 * hidden), jnp.float32),
        ),
        in_specs=[
            pl.BlockSpec((t3, R, 3 * hidden), tmap, memory_space=pltpu.VMEM),
            pl.BlockSpec((t3, R, hidden), tmap, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, R, hidden), tmap_prev, memory_space=pltpu.VMEM),
            pl.BlockSpec((t3, R, hidden), tmap, memory_space=pltpu.VMEM),
            pl.BlockSpec((S, hidden, 3 * hidden), const,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((S, 1, 3 * hidden), const, memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((t3, R, 3 * hidden), tmap, memory_space=pltpu.VMEM),
            pl.BlockSpec((S, hidden, 3 * hidden), const,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((S, 1, 3 * hidden), const, memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[pltpu.VMEM((R, hidden), jnp.float32)],
        interpret=interpret,
    )(xs, hs, hs_bound, dy, w_hh.astype(cdt), b_hh.reshape(S, 1, 3 * hidden))

    return _finish_bwd(
        flags, w_ih, b_ih, w_hh, b_hh, x, dxp, dwhh, dbhh, B, Bp, hidden
    )


_gru_multi.defvjp(_gru_multi_fwd, _gru_multi_bwd)


def _dir_arrays(params_list):
    w_ih = jnp.stack([p["w_ih"] for p in params_list])
    b_ih = jnp.stack([p["b_ih"] for p in params_list])
    w_hh = jnp.stack([p["w_hh"] for p in params_list])
    b_hh = jnp.stack([p["b_hh"] for p in params_list])
    return w_ih, b_ih, w_hh, b_hh


def gru_direction_pallas(
    params: Dict[str, jax.Array],
    x: jax.Array,  # [B, T, in]
    reverse: bool = False,
    *,
    interpret: bool = False,
    compute_dtype=jnp.float32,
) -> jax.Array:
    """One direction of one GRU layer, [B,T,in] -> [B,T,H]; numerics
    match roko_tpu.models.gru.gru_direction (same gate math, float32
    hidden accumulation). Differentiable via the fused backward kernel."""
    static = ((bool(reverse),), bool(interpret), jnp.dtype(compute_dtype).name)
    ys = _gru_multi(static, *_dir_arrays([params]), x)
    return ys[0]


def fused_bidir_layer(
    layer: Dict[str, Dict[str, jax.Array]],
    x: jax.Array,
    *,
    interpret: bool = False,
    compute_dtype=jnp.float32,
) -> jax.Array:
    """One bidirectional layer in a single kernel launch:
    [B,T,in] -> [B,T,2H] (fwd ++ bwd on the feature axis)."""
    static = ((False, True), bool(interpret), jnp.dtype(compute_dtype).name)
    ys = _gru_multi(static, *_dir_arrays([layer["fwd"], layer["bwd"]]), x)
    return jnp.concatenate([ys[0], ys[1]], axis=-1)


def bidir_gru_stack_pallas(
    params,
    x: jax.Array,
    *,
    dropout: float = 0.0,
    deterministic: bool = True,
    rng: jax.Array | None = None,
    interpret: bool = False,
    compute_dtype=jnp.float32,
) -> jax.Array:
    """Stacked bidirectional GRU on the fused kernels, [B,T,in] ->
    [B,T,2H]. Train-capable: the custom VJP backs propagation through
    every layer; dropout (between layers only, torch.nn.GRU placement)
    is applied outside the kernels."""
    num_layers = len(params)
    for i, layer in enumerate(params):
        x = fused_bidir_layer(
            layer, x, interpret=interpret, compute_dtype=compute_dtype
        )
        if dropout > 0.0 and not deterministic and i < num_layers - 1:
            assert rng is not None
            rng, sub = jax.random.split(rng)
            x = _dropout(sub, x, dropout)
    return x.astype(jnp.float32)
