"""Fused Pallas TPU kernel for the GRU recurrence.

The hot loop of the consensus model is 90 timesteps x 2 directions x 3
layers of GRU steps (SURVEY.md §7 "hard parts" (a)). The lax.scan path
re-materialises the hidden state through HBM every step; this kernel
runs one whole direction's recurrence inside a single Pallas program
with the hidden state pinned in a VMEM scratch buffer, so the serial
chain touches HBM only for the per-step x-projection read and output
write.

Layout choices:
- the input projection ``x @ W_ih + b_ih`` stays OUTSIDE the kernel —
  one large [B*T, in] x [in, 3H] MXU matmul that XLA already schedules
  well (same hoisting as the scan path, roko_tpu/models/gru.py:11-14);
- time-major [T, B, 3H] so the serial loop indexes the leading axis;
- x_proj is cast to the model compute dtype for the VMEM residency
  (bfloat16 halves VMEM pressure: [90,128,384] bf16 = 8.8 MB); the
  recurrence itself accumulates in float32;
- H=128 keeps every matmul lane-aligned (MXU 128x128).

The kernel is inference-only: training keeps the lax.scan path (whose
VJP XLA derives automatically). ``interpret=True`` makes the same
kernel run on CPU for tests.
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _gru_kernel(T: int, hidden: int, reverse: bool, out_dtype):
    def kernel(xp_ref, whh_ref, bhh_ref, out_ref, h_scratch):
        h_scratch[...] = jnp.zeros_like(h_scratch)

        def step(i, _):
            t = (T - 1 - i) if reverse else i
            xp = xp_ref[t].astype(jnp.float32)  # [B, 3H]
            h = h_scratch[...]
            hp = (
                jnp.dot(
                    h,
                    whh_ref[...].astype(jnp.float32),
                    preferred_element_type=jnp.float32,
                )
                + bhh_ref[...].astype(jnp.float32)
            )
            r = jax.nn.sigmoid(xp[:, :hidden] + hp[:, :hidden])
            z = jax.nn.sigmoid(
                xp[:, hidden : 2 * hidden] + hp[:, hidden : 2 * hidden]
            )
            n = jnp.tanh(xp[:, 2 * hidden :] + r * hp[:, 2 * hidden :])
            h_new = (1.0 - z) * n + z * h
            h_scratch[...] = h_new
            out_ref[t] = h_new.astype(out_dtype)
            return 0

        jax.lax.fori_loop(0, T, step, 0)

    return kernel


@functools.partial(
    jax.jit, static_argnames=("reverse", "interpret", "compute_dtype")
)
def gru_direction_pallas(
    params: Dict[str, jax.Array],
    x: jax.Array,  # [B, T, in]
    reverse: bool = False,
    *,
    interpret: bool = False,
    compute_dtype=jnp.float32,
) -> jax.Array:
    """One direction of one GRU layer, [B,T,in] -> [B,T,H]; numerics
    match roko_tpu.models.gru.gru_direction (same gate math, float32
    accumulation)."""
    hidden = params["w_hh"].shape[0]
    B, T, _ = x.shape

    x_proj = x @ params["w_ih"] + params["b_ih"]  # [B,T,3H] big MXU matmul
    x_proj = x_proj.swapaxes(0, 1).astype(compute_dtype)  # [T,B,3H]

    # batch-block the grid so x_proj residency stays within VMEM: Pallas
    # double-buffers in/out blocks, so the budget is 2x(x_proj block +
    # out block); [90, 64, 384] bf16 = 4.4 MB keeps the total ~12 MB.
    # Blocks are independent recurrences, so the sequential TPU grid
    # just re-runs the T-loop per block. Odd batch sizes are padded up to
    # the block multiple (zero rows recur independently; sliced off).
    b_blk = B if B <= 64 else 64
    pad = (-B) % b_blk
    if pad:
        x_proj = jnp.concatenate(
            [x_proj, jnp.zeros((T, pad, x_proj.shape[2]), x_proj.dtype)], axis=1
        )

    Bp = B + pad
    out = pl.pallas_call(
        _gru_kernel(T, hidden, reverse, x_proj.dtype),
        grid=(Bp // b_blk,),
        out_shape=jax.ShapeDtypeStruct((T, Bp, hidden), x_proj.dtype),
        in_specs=[
            pl.BlockSpec((T, b_blk, 3 * hidden), lambda i: (0, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((hidden, 3 * hidden), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 3 * hidden), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((T, b_blk, hidden), lambda i: (0, i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((b_blk, hidden), jnp.float32)],
        interpret=interpret,
    )(x_proj, params["w_hh"], params["b_hh"].reshape(1, -1))

    if pad:
        out = out[:, :B]
    # stay in compute_dtype between layers so the next layer's hoisted
    # input projection keeps bf16 MXU throughput; the stack casts the
    # final output to f32
    return out.swapaxes(0, 1)  # [B,T,H] compute_dtype


def bidir_gru_stack_pallas(
    params,
    x: jax.Array,
    *,
    interpret: bool = False,
    compute_dtype=jnp.float32,
) -> jax.Array:
    """Stacked bidirectional GRU on the fused kernel, [B,T,in] ->
    [B,T,2H]. Inference only (no dropout, no VJP)."""
    for layer in params:
        fwd = gru_direction_pallas(
            layer["fwd"], x, False, interpret=interpret, compute_dtype=compute_dtype
        )
        bwd = gru_direction_pallas(
            layer["bwd"], x, True, interpret=interpret, compute_dtype=compute_dtype
        )
        x = jnp.concatenate([fwd, bwd], axis=-1)
    return x.astype(jnp.float32)
