"""Shared primitive layers: torch-parity Linear init, inverted dropout,
layernorm. One copy, consumed by both model families, so checkpoint
conversion parity (torch nn.Linear's U(-1/sqrt(in), 1/sqrt(in)) init and
torch dropout scaling) is defined in exactly one place."""

from __future__ import annotations

import math
from typing import Dict

import jax
import jax.numpy as jnp


def dense_params(rng, in_dim: int, out_dim: int, dtype=jnp.float32) -> Dict:
    kkernel, kbias = jax.random.split(rng)
    # torch nn.Linear default: U(-1/sqrt(in), 1/sqrt(in)) for both
    bound = 1.0 / math.sqrt(in_dim)
    return {
        "kernel": jax.random.uniform(
            kkernel, (in_dim, out_dim), dtype, -bound, bound
        ),
        "bias": jax.random.uniform(kbias, (out_dim,), dtype, -bound, bound),
    }


def dense(p: Dict, x: jax.Array) -> jax.Array:
    return x @ p["kernel"] + p["bias"]


def dropout(rng, x: jax.Array, rate: float) -> jax.Array:
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


def layernorm(p: Dict, x: jax.Array, eps: float = 1e-5) -> jax.Array:
    mu = x.mean(axis=-1, keepdims=True)
    var = x.var(axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * p["scale"] + p["bias"]


def layernorm_params(dim: int, dtype=jnp.float32) -> Dict:
    return {"scale": jnp.ones((dim,), dtype), "bias": jnp.zeros((dim,), dtype)}


def cast_tree(tree, dtype):
    """Cast every float leaf to ``dtype`` (int leaves untouched)."""
    return jax.tree.map(
        lambda a: a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating) else a,
        tree,
    )
