"""Shared primitive layers: torch-parity Linear init, inverted dropout,
layernorm. One copy, consumed by both model families, so checkpoint
conversion parity (torch nn.Linear's U(-1/sqrt(in), 1/sqrt(in)) init and
torch dropout scaling) is defined in exactly one place."""

from __future__ import annotations

import math
from typing import Dict

import jax
import jax.numpy as jnp


def dense_params(rng, in_dim: int, out_dim: int, dtype=jnp.float32) -> Dict:
    kkernel, kbias = jax.random.split(rng)
    # torch nn.Linear default: U(-1/sqrt(in), 1/sqrt(in)) for both
    bound = 1.0 / math.sqrt(in_dim)
    return {
        "kernel": jax.random.uniform(
            kkernel, (in_dim, out_dim), dtype, -bound, bound
        ),
        "bias": jax.random.uniform(kbias, (out_dim,), dtype, -bound, bound),
    }


def is_quantized_weight(w) -> bool:
    """True for the int8 weight-only representation models/quant.py
    emits: ``{"q": int8[..., out], "scale": f32[out]}``."""
    return isinstance(w, dict) and "q" in w and "scale" in w


def dequant_weight(w: Dict, dtype=None) -> jax.Array:
    """int8 weight dict -> dense kernel in ``dtype`` (default f32).
    The multiply runs in f32 — scales are exact f32 per output channel
    — and casts once at the end; inside a jitted apply this is the
    dequant-in-matmul pattern: the bytes streamed from HBM are the int8
    ``q``, the f32/bf16 kernel exists only as a fused temporary."""
    kernel = w["q"].astype(jnp.float32) * w["scale"]
    return kernel.astype(dtype) if dtype is not None else kernel


def weight(w, dtype=None) -> jax.Array:
    """The one idiom every matmul site fetches its kernel through: a
    plain array casts to ``dtype`` (a no-op everywhere params were
    already cast), an int8 weight dict dequantizes in place
    (models/quant.py)."""
    if is_quantized_weight(w):
        return dequant_weight(w, dtype)
    return w.astype(dtype) if dtype is not None else w


def dense(p: Dict, x: jax.Array) -> jax.Array:
    return x @ weight(p["kernel"], x.dtype) + p["bias"]


def dropout(rng, x: jax.Array, rate: float) -> jax.Array:
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


def layernorm(p: Dict, x: jax.Array, eps: float = 1e-5) -> jax.Array:
    mu = x.mean(axis=-1, keepdims=True)
    var = x.var(axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * p["scale"] + p["bias"]


def layernorm_params(dim: int, dtype=jnp.float32) -> Dict:
    return {"scale": jnp.ones((dim,), dtype), "bias": jnp.zeros((dim,), dtype)}


def cast_tree(tree, dtype):
    """Cast every float leaf to ``dtype`` (int leaves untouched).
    Quantized weight dicts pass through whole: their int8 payload is
    already the storage format and their f32 scales must STAY f32 —
    dequantization casts to the compute dtype at the use site
    (``weight``)."""

    def cast(a):
        if is_quantized_weight(a):
            return a
        return a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating) else a

    return jax.tree.map(cast, tree, is_leaf=is_quantized_weight)
