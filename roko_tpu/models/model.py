"""The roko consensus network, TPU-native.

Architecture (semantics ref: roko/rnn_model.py:24-59, shapes documented in
SURVEY.md §3.5):

```
x: int[B,200,90] (0-11)
embed(12,50)   -> [B,200,90,50]   dropout
transpose      -> [B,90,50,200]   (read axis last)
fc1 200->100   -> relu, dropout
fc2 100->10    -> relu, dropout
reshape        -> [B,90,500]
bidir GRU x3 h=128 -> [B,90,256]
head 256->5    -> logits [B,90,5]
```

Three recurrence families share that skeleton behind ``ModelConfig.kind``:
``"gru"`` (the torch-exact reference above), ``"lingru"`` (associative-
scan gated linear recurrence, log-depth in T — models/lingru.py), and
``"transformer"``. The front end and head are identical across kinds, so
only the [B,90,500] -> [B,90,256] block differs.

Implemented as a functional param-pytree model (no framework Module): the
params dict is the single source of truth, which keeps torch-checkpoint
conversion (`roko_tpu/models/convert.py`), Orbax serialisation and pjit
sharding specs trivial. All dense contractions are large batched matmuls
that tile directly onto the MXU; `compute_dtype="bfloat16"` casts the
matmul operands while keeping params and the final logits in float32.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from roko_tpu import constants as C
from roko_tpu.config import ModelConfig
from roko_tpu.models.gru import RokoGRU
from roko_tpu.models.lingru import RokoLinGRU
from roko_tpu.models.layers import (
    cast_tree,
    dense as _dense,
    dense_params as _dense_params,
    dropout as _dropout,
    weight as _weight,
)

Params = Dict[str, Any]


class RokoModel:
    """Functional model: ``init`` builds the param pytree, ``apply`` runs
    the forward pass. ``apply`` is pure and jit/shard_map friendly."""

    def __init__(self, cfg: Optional[ModelConfig] = None, attn_fn=None):
        """``attn_fn`` injects a custom attention (e.g. the ring
        sequence-parallel one from roko_tpu/parallel/ring.py) into the
        transformer variant; None uses dense attention."""
        # "auto" resolves to the live backend's default here — bf16 on
        # TPU, f32 elsewhere (config.default_compute_dtype) — so apply,
        # the AOT bundle identity, and the bench suites all agree on
        # the concrete dtype
        self.cfg = (cfg or ModelConfig()).resolve()
        self.attn_fn = attn_fn
        if self.cfg.kind not in ("gru", "lingru", "transformer"):
            raise ValueError(f"unknown model kind: {self.cfg.kind}")
        if self.cfg.kind == "transformer":
            # fail at construction, not first init/apply, if the variant
            # is unavailable
            from roko_tpu.models import transformer  # noqa: F401
        self.gru = RokoGRU(
            self.cfg.gru_in_size,
            self.cfg.hidden_size,
            self.cfg.num_layers,
            self.cfg.dropout,
            use_pallas=self.cfg.use_pallas,
            remat_scan=self.cfg.remat_scan,
        )
        # stateless container — built unconditionally, like self.gru
        self.lingru = RokoLinGRU(
            self.cfg.gru_in_size,
            self.cfg.hidden_size,
            self.cfg.num_layers,
            self.cfg.dropout,
            use_pallas=self.cfg.use_pallas,
        )

    # -- init ---------------------------------------------------------------
    def init(self, rng: jax.Array) -> Params:
        cfg = self.cfg
        keys = jax.random.split(rng, 5)
        params: Params = {
            # torch nn.Embedding default init: N(0, 1)
            "embedding": jax.random.normal(
                keys[0], (cfg.embed_vocab, cfg.embed_dim), jnp.float32
            ),
            "fc1": _dense_params(keys[1], cfg.window_rows, cfg.read_mlp[0]),
            "fc2": _dense_params(keys[2], cfg.read_mlp[0], cfg.read_mlp[1]),
            "head": _dense_params(
                keys[3], 2 * cfg.hidden_size, cfg.num_classes
            ),
        }
        if cfg.kind == "gru":
            params["gru"] = self.gru.init(keys[4])
        elif cfg.kind == "lingru":
            params["lingru"] = self.lingru.init(keys[4])
        else:  # transformer params built in models/transformer.py
            from roko_tpu.models.transformer import transformer_init

            params["encoder"] = transformer_init(keys[4], cfg)
        if cfg.quantize is not None:
            # a quantized config's NATIVE tree is the quantized one:
            # `roko-tpu compile --quantize int8` lowers against this
            # structure (eval_shape — quantization is traceable), and
            # tests/bench init real quantized params the same way
            from roko_tpu.models.quant import quantize_params

            params = quantize_params(params, cfg)
        return params

    # -- forward ------------------------------------------------------------
    def apply(
        self,
        params: Params,
        x: jax.Array,  # int[B,200,90]
        *,
        deterministic: bool = True,
        rng: Optional[jax.Array] = None,
    ) -> jax.Array:
        cfg = self.cfg
        dtype = jnp.dtype(cfg.compute_dtype)
        train = not deterministic
        if train:
            assert rng is not None, "training forward needs a dropout rng"
            rngs = list(jax.random.split(rng, 4))

        # Both paths avoid the embedding gather: with a 12-word vocab a
        # one-hot matmul has exactly one nonzero term scaled by 1.0 per
        # output element, so it is BIT-identical to jnp.take — and both
        # its forward and its backward (the train-step hot spot: a
        # 9.2M-row scatter-add) become MXU GEMMs.
        if train:

            def _front(p_sub, x, r0, r1, r2):
                # The per-element dropout between embed and fc1
                # (reference placement, roko/rnn_model.py:47-49) forces
                # materialising e, so the inference-only reassociation
                # below can't be used here; the read-axis contraction is
                # left to einsum so XLA picks the layout instead of
                # paying an explicit 920 MB transpose. The one-hot is
                # computed inside so a remat boundary stores only the
                # uint8 x, not 221 MB of one-hot.
                onehot = jax.nn.one_hot(x, cfg.embed_vocab, dtype=dtype)
                e = jnp.einsum(
                    "brtv,vd->brtd", onehot, p_sub["embedding"]
                )  # [B,200,90,50]
                e = _dropout(r0, e, cfg.dropout)
                h = jnp.einsum(
                    "brtd,rj->btdj", e, _weight(p_sub["fc1"]["kernel"], dtype)
                )
                h = jax.nn.relu(h + p_sub["fc1"]["bias"])
                h = _dropout(r1, h, cfg.dropout)
                h = jax.nn.relu(_dense(p_sub["fc2"], h))
                return _dropout(r2, h, cfg.dropout)

            p_sub = {
                "embedding": params["embedding"].astype(dtype),
                "fc1": cast_tree(params["fc1"], dtype),
                "fc2": cast_tree(params["fc2"], dtype),
            }
            # remat: recompute this chain in the backward (same rngs ->
            # identical masks, identical values) instead of streaming
            # ~1.8 GB of activations + masks through HBM per batch-512
            # step; see ModelConfig.remat_frontend
            front = jax.checkpoint(_front) if cfg.remat_frontend else _front
            h = front(p_sub, x, rngs[0], rngs[1], rngs[2])
        else:
            # Inference fast path: embedding-gather + transpose + fc1 is
            # algebraically  relu(E[x]^T(r-axis) @ W1 + b1)  =
            # relu(E^T @ (onehot(x)^T(r) @ W1) + b1)  because the vocab is
            # tiny (12). Reassociating turns a 230 MB gather + relayout
            # (the measured hot spot: ~59 ms of a 75 ms batch-128 forward
            # on v5e) into two MXU einsums over a [*,12] axis. Same math
            # as the reference chain (roko/rnn_model.py:47-51) up to float
            # summation order; only valid without the per-element dropout
            # between embed and fc1, hence inference-only.
            onehot = jax.nn.one_hot(x, cfg.embed_vocab, dtype=dtype)
            # weight() dequantizes an int8 weight-only kernel in place
            w1 = _weight(params["fc1"]["kernel"], dtype)  # [200, J]
            # contract the read axis first: [B,T,V,J]
            m = jnp.einsum("brtv,rj->btvj", onehot, w1)
            emb = params["embedding"].astype(dtype)  # [V, D]
            h = jnp.einsum("vd,btvj->btdj", emb, m)  # [B,T,D,J]
            h = jax.nn.relu(h + params["fc1"]["bias"].astype(dtype))
            h = jax.nn.relu(_dense(cast_tree(params["fc2"], dtype), h))

        # [B,90,50,10] -> [B,90,500]; row-major flatten matches the
        # reference's .reshape(-1, 90, 500)
        B = h.shape[0]
        h = h.reshape(B, cfg.window_cols, cfg.gru_in_size)

        if cfg.kind == "gru":
            h = self.gru.apply(
                cast_tree(params["gru"], dtype),
                h,
                deterministic=deterministic,
                rng=rngs[3] if train else None,
            )
        elif cfg.kind == "lingru":
            h = self.lingru.apply(
                cast_tree(params["lingru"], dtype),
                h,
                deterministic=deterministic,
                rng=rngs[3] if train else None,
            )
        else:
            from roko_tpu.models.transformer import attention, transformer_apply

            h = transformer_apply(
                cast_tree(params["encoder"], dtype),
                self.cfg,
                h,
                deterministic=deterministic,
                rng=rngs[3] if train else None,
                attn_fn=self.attn_fn or attention,
            )

        logits = _dense(params["head"], h.astype(jnp.float32))
        return logits  # [B,90,num_classes] float32


def build_model(cfg: Optional[ModelConfig] = None) -> RokoModel:
    return RokoModel(cfg)


def init_params(rng: jax.Array, cfg: Optional[ModelConfig] = None) -> Params:
    return RokoModel(cfg).init(rng)
