"""One-shot torch -> param-pytree checkpoint converter.

Maps the reference's ``state_dict`` layout (ref: roko/rnn_model.py —
``embedding``, ``fc1``, ``fc2``, ``gru.weight_ih_l{k}[_reverse]``,
``fc4``) onto :class:`roko_tpu.models.RokoModel` params, so the published
``r10_2.3.8.pth`` checkpoint (ref: README.md:115) runs unchanged on TPU.

Layout differences handled here:
- torch ``nn.Linear.weight`` is [out, in]; we store [in, out] kernels.
- torch GRU weights are [3H, in] with gate order (r, z, n); we store the
  transpose [in, 3H] with the same gate order, so no gate reshuffling is
  needed — only a transpose.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping

import numpy as np

from roko_tpu.config import ModelConfig
from roko_tpu.models.model import Params


def _np(t: Any) -> np.ndarray:
    """torch.Tensor | ndarray -> float32 ndarray (no torch import needed
    unless a tensor is actually passed)."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t, dtype=np.float32)


def from_torch_state_dict(
    sd: Mapping[str, Any], cfg: ModelConfig | None = None
) -> Params:
    cfg = cfg or ModelConfig()
    if cfg.kind != "gru":
        raise ValueError("torch conversion only defined for the GRU model")

    params: Dict[str, Any] = {
        "embedding": _np(sd["embedding.weight"]),
        "fc1": {"kernel": _np(sd["fc1.weight"]).T, "bias": _np(sd["fc1.bias"])},
        "fc2": {"kernel": _np(sd["fc2.weight"]).T, "bias": _np(sd["fc2.bias"])},
        "head": {"kernel": _np(sd["fc4.weight"]).T, "bias": _np(sd["fc4.bias"])},
    }

    layers = []
    for k in range(cfg.num_layers):
        layer = {}
        for direction, suffix in (("fwd", ""), ("bwd", "_reverse")):
            layer[direction] = {
                "w_ih": _np(sd[f"gru.weight_ih_l{k}{suffix}"]).T,
                "w_hh": _np(sd[f"gru.weight_hh_l{k}{suffix}"]).T,
                "b_ih": _np(sd[f"gru.bias_ih_l{k}{suffix}"]),
                "b_hh": _np(sd[f"gru.bias_hh_l{k}{suffix}"]),
            }
        layers.append(layer)
    params["gru"] = tuple(layers)
    return params


def load_torch_checkpoint(path: str, cfg: ModelConfig | None = None) -> Params:
    """Load a reference ``.pth`` state_dict file (requires torch)."""
    import torch

    sd = torch.load(path, map_location="cpu")
    if not isinstance(sd, Mapping) or "embedding.weight" not in sd:
        raise ValueError(f"{path} does not look like a roko RNN state_dict")
    return from_torch_state_dict(sd, cfg)
