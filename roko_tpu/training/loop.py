"""Jitted, mesh-sharded train/eval steps and the epoch driver.

Replaces the reference's ignite Engine pair + callbacks (ref:
roko/train.py:41-111) with an explicit loop: Adam(1e-4), cross-entropy
over the 5 base classes at every one of the 90 window columns, per-epoch
validation accuracy, early stopping with patience 7, best-k Orbax
checkpoints (ref hyperparams: roko/train.py:12-15,39,74-84).

TPU mapping: params and optimizer state are replicated over the mesh,
batches are sharded over the ``dp`` axis (`PartitionSpec("dp")`), and the
gradient all-reduce is the `psum` XLA inserts for the replicated-output
jit — no hand-written collectives (SURVEY.md §2 north-star row "Data
parallel (training)").
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from roko_tpu.config import RokoConfig
from roko_tpu.models.model import RokoModel
from roko_tpu.obs import events as obs_events
from roko_tpu.parallel.mesh import (
    AXIS_DP,
    data_sharding,
    make_mesh,
    put_replicated,
    replicated_sharding,
)
from roko_tpu.training import checkpoint as ckpt_lib
from roko_tpu.training.data import prefetch_to_device
from roko_tpu.utils.profiling import device_trace

Params = Dict[str, Any]


@dataclasses.dataclass
class TrainState:
    params: Params
    opt_state: Any
    step: jax.Array  # scalar int32

    def as_dict(self) -> Dict[str, Any]:
        return {"params": self.params, "opt_state": self.opt_state, "step": self.step}


def create_state(
    model: RokoModel, tx: optax.GradientTransformation, rng: jax.Array
) -> TrainState:
    params = model.init(rng)
    return TrainState(params, tx.init(params), jnp.zeros((), jnp.int32))


def _loss_and_stats(model, params, x, y, w, rng):
    """Mean CE over real rows + summed correct/total counts.

    ``w`` is a per-row weight (0 for padding rows) so fixed-shape sharded
    batches don't bias the metrics.
    """
    logits = model.apply(
        params, x, deterministic=rng is None, rng=rng
    )  # [B,90,5] f32
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, y[..., None].astype(jnp.int32), axis=-1)[..., 0]
    per_row = -ll.mean(axis=-1)  # [B] mean over 90 columns
    denom = jnp.maximum(w.sum(), 1.0)
    loss = (per_row * w).sum() / denom
    pred = jnp.argmax(logits, axis=-1)
    correct = ((pred == y) * w[:, None]).sum()
    total = w.sum() * y.shape[1]
    return loss, (correct, total)


def _donate_state_argnums(mesh: Mesh, argnums: Tuple[int, ...]) -> Tuple[int, ...]:
    """Donate params/opt_state buffers only on a pure-dp mesh. With a
    tensor- or sequence-parallel axis the inputs carry committed
    NamedShardings while the step's out_shardings stay None (XLA's
    choice), and this jaxlib crashes at dispatch trying to alias the
    mismatched layouts (INTERNAL "Expected aliased input ... to have the
    same size") instead of quietly dropping the donation. Donation never
    bought anything there anyway — the same runs warned "donated buffers
    were not usable" on older jaxlibs."""
    from roko_tpu.parallel.mesh import AXIS_SP, AXIS_TP

    if mesh.shape.get(AXIS_TP, 1) > 1 or mesh.shape.get(AXIS_SP, 1) > 1:
        return ()
    return argnums


def make_train_step(
    model: RokoModel, tx: optax.GradientTransformation, mesh: Mesh
) -> Callable:
    repl = replicated_sharding(mesh)
    data = data_sharding(mesh)

    # params/opt_state shardings are None: the step preserves whatever
    # placement the caller chose (replicated for the GRU family,
    # tensor-parallel NamedShardings from parallel/tp.py for the
    # transformer), so the same step function serves dp and dp+tp.
    @partial(
        jax.jit,
        in_shardings=(None, None, repl, data, data, data, repl),
        out_shardings=(None, None, repl, repl),
        donate_argnums=_donate_state_argnums(mesh, (0, 1)),
    )
    def step(params, opt_state, step_no, x, y, w, rng):
        rng = jax.random.fold_in(rng, step_no)

        def loss_fn(p):
            loss, aux = _loss_and_stats(model, p, x, y, w, rng)
            return loss, aux

        (loss, (correct, total)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, correct / jnp.maximum(total, 1.0)

    return step


def make_guarded_train_step(
    model: RokoModel, tx: optax.GradientTransformation, mesh: Mesh
) -> Tuple[Callable, Callable]:
    """Two-phase train step for the NaN/loss-spike sentinel
    (roko_tpu/training/guard.py): ``grad_step`` computes grads plus
    host-checkable flags WITHOUT donating or touching params, the host
    decides (TrainGuard.check), and only a good step re-dispatches
    ``apply_step`` — which donates params/opt_state/grads exactly like
    the fused step. A bad step simply never dispatches the apply, so the
    pre-step params survive untouched; deciding after a fused donating
    step would be too late, the old buffers are already gone.

    Returns ``(grad_step, apply_step)``:

    - ``grad_step(params, step_no, x, y, w, rng) ->
      (grads, loss, acc, grads_finite)`` — ``grads_finite`` is a
      replicated bool covering the loss and every gradient leaf;
    - ``apply_step(params, opt_state, grads) ->
      (params, opt_state, params_finite)`` — ``params_finite`` catches
      optimizer-math overflow (finite grads, non-finite update).
    """
    repl = replicated_sharding(mesh)
    data = data_sharding(mesh)

    @partial(
        jax.jit,
        in_shardings=(None, repl, data, data, data, repl),
        out_shardings=(None, repl, repl, repl),
    )
    def grad_step(params, step_no, x, y, w, rng):
        rng = jax.random.fold_in(rng, step_no)

        def loss_fn(p):
            loss, aux = _loss_and_stats(model, p, x, y, w, rng)
            return loss, aux

        (loss, (correct, total)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params)
        finite = jnp.isfinite(loss)
        for leaf in jax.tree.leaves(grads):
            finite = jnp.logical_and(finite, jnp.isfinite(leaf).all())
        return grads, loss, correct / jnp.maximum(total, 1.0), finite

    # donate params/opt_state only, exactly like the fused step: the
    # outputs can reuse at most params+opt_state worth of buffers, so a
    # donated grads tree would just trip the unusable-donation warning
    @partial(
        jax.jit,
        in_shardings=(None, None, None),
        out_shardings=(None, None, repl),
        donate_argnums=_donate_state_argnums(mesh, (0, 1)),
    )
    def apply_step(params, opt_state, grads):
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        finite = jnp.asarray(True)
        for leaf in jax.tree.leaves(params):
            finite = jnp.logical_and(finite, jnp.isfinite(leaf).all())
        return params, opt_state, finite

    return grad_step, apply_step


def make_eval_step(model: RokoModel, mesh: Mesh) -> Callable:
    repl = replicated_sharding(mesh)
    data = data_sharding(mesh)

    @partial(
        jax.jit,
        in_shardings=(None, data, data, data),
        out_shardings=(repl, repl, repl),
    )
    def step(params, x, y, w):
        loss, (correct, total) = _loss_and_stats(model, params, x, y, w, None)
        return loss, correct, total

    return step


def make_placer(mesh: Mesh, *, local_rows: bool = False) -> Callable:
    """Host->device placement for a (x, y, w)-style tuple of batches,
    correct on multi-host pods.

    Single process: a plain ``device_put`` onto the dp sharding. With
    ``jax.process_count() > 1`` a host cannot ``device_put`` onto a mesh
    spanning non-addressable devices; instead every process wraps its
    own rows with ``jax.make_array_from_process_local_data``, which
    assembles the logically-global array from per-process shards
    (SURVEY.md §5.8; VERDICT r2 task #3). Row-slice <-> device locality
    holds because ``jax.devices()`` orders devices process-major and the
    mesh's dp axis follows that order.

    ``local_rows=False`` (the legacy contract): every process generated
    the identical GLOBAL batch and slices out its rows here.
    ``local_rows=True`` (the sharded data plane): each process feeds
    only its own shard's rows — the global batch is their process-order
    concatenation, and no host ever generated rows it doesn't own."""
    sharding = data_sharding(mesh)
    nproc = jax.process_count()
    pid = jax.process_index()

    def place(batch):
        if nproc == 1:
            return tuple(jax.device_put(a, sharding) for a in batch)
        out = []
        for a in batch:
            if local_rows:
                local = a
                global_shape = (a.shape[0] * nproc,) + a.shape[1:]
            else:
                if a.shape[0] % nproc:
                    raise ValueError(
                        f"global batch {a.shape[0]} not divisible by "
                        f"{nproc} processes"
                    )
                per = a.shape[0] // nproc
                local = a[pid * per : (pid + 1) * per]
                global_shape = a.shape
            out.append(
                jax.make_array_from_process_local_data(
                    sharding, local, global_shape
                )
            )
        return tuple(out)

    return place


def evaluate(eval_step, params, dataset, batch_size, mesh) -> Tuple[float, float]:
    """Return (mean position accuracy, mean per-window loss)."""
    place = make_placer(mesh)

    correct = total = 0.0
    loss_sum = rows = 0.0
    it = dataset.batches(batch_size, pad_to=batch_size)
    for x, y, w in prefetch_to_device(it, 2, place):
        n_rows = float(w.sum())
        loss, c, t = eval_step(params, x, y, w)
        loss_sum += float(loss) * n_rows
        rows += n_rows
        correct += float(c)
        total += float(t)
    return correct / max(total, 1.0), loss_sum / max(rows, 1.0)


def _warn_if_cpu_mesh_oversubscribed(mesh: Mesh, log) -> None:
    """A virtual CPU mesh wider than the physical core count is a
    correctness hazard, not just slow: XLA CPU collectives require every
    participant's thread to reach the rendezvous within ~40s, and when
    the per-device shard computation itself takes tens of seconds the
    devices execute serially on the contended cores, so device 0 can
    wait out the timeout before device N-1 even starts — rendezvous.cc
    then F-aborts the process ("Expected N threads to join ... not all
    of them arrived"). Observed in r5 with the full model at dp=8 on a
    1-core host; tiny-model tests never trip it. Warn loudly so the
    user reaches for --dp 1 before the crash does."""
    import os

    n_mesh = int(np.prod(list(mesh.shape.values())))
    cores = os.cpu_count() or 1
    if n_mesh > 1 and cores < n_mesh and mesh.devices.flat[0].platform == "cpu":
        log(
            f"WARNING: {n_mesh}-device CPU mesh on {cores} core(s) — XLA "
            "CPU collectives can hit their rendezvous timeout and abort "
            "when per-device compute is heavy; use --dp 1 (or fewer "
            "devices than cores) for full-size models on small hosts"
        )


def train(
    cfg: RokoConfig,
    train_path: str,
    out_dir: str,
    val_path: Optional[str] = None,
    *,
    mesh: Optional[Mesh] = None,
    resume: bool = True,
    trace_dir: Optional[str] = None,
    log: Callable[[str], None] = print,
) -> TrainState:
    """Full training run; returns the final state. Best-k checkpoints by
    validation accuracy land in ``out_dir`` (ref flow: roko/train.py:18-111).

    Checkpoints carry optimizer state, step, epoch, the early-stopping
    counters, AND the data-pipeline position (epoch, batch index,
    applied-update count, running loss sum) so an interrupted run
    resumes from exactly the next untrained batch and finishes with
    bit-identical params and loss curve to an uninterrupted run
    (docs/TRAINING.md "Failure handling"); every save commits a sha256
    manifest and restore walks a verified fallback chain
    (checkpoint.py). With ``cfg.guard.enabled`` the NaN/loss-spike
    sentinel (guard.py) skips bad updates and rolls back to the last
    good checkpoint — with a re-jittered dropout stream — after
    ``max_bad_steps`` consecutive bad steps.

    Multi-host pods: call-site needs nothing special — ``train()``
    initialises ``jax.distributed`` when a pod topology is detected, the
    mesh spans all hosts' devices, every process feeds its slice of the
    global batch (``make_placer``), logging is primary-only, and every
    process participates in checkpoint save/restore (the Orbax
    multi-host contract: process 0 writes metadata, all processes write
    their addressable shards — gating save on the primary would
    deadlock sharded arrays). Guard decisions read replicated scalars,
    so every process skips/rolls back in lockstep."""
    from roko_tpu.parallel import distributed
    from roko_tpu.training import guard as guard_lib

    distributed.initialize()  # no-op single host (SURVEY §5.8)
    if cfg.model.quantize is not None:
        # quantization is CONVERSION-TIME only (docs/TRAINING.md):
        # training runs full precision (f32 or bf16 compute) and the
        # int8 conversion happens when the checkpoint is loaded for
        # inference/serve or AOT-compiled (`roko-tpu compile --quantize`)
        raise ValueError(
            f"quantize={cfg.model.quantize!r} is an inference-only "
            "conversion: train full precision, then quantize at load "
            "time (--quantize int8 on inference/polish/serve/compile)"
        )
    if not distributed.is_primary():
        log = lambda s: None  # noqa: E731 — primary-only logging
    tcfg = cfg.train
    gcfg = cfg.guard
    dcfg = cfg.data
    mesh = mesh or make_mesh(cfg.mesh)
    dp = mesh.shape[AXIS_DP]
    if tcfg.batch_size % dp:
        raise ValueError(
            f"batch_size {tcfg.batch_size} not divisible by dp={dp}"
        )
    _warn_if_cpu_mesh_oversubscribed(mesh, log)

    # -- sharded input data plane (roko_tpu/datapipe, docs/TRAINING.md
    # "Sharded input pipeline"): resolve the shard spec, index the file
    # set, and stream only this host's span blocks
    from roko_tpu.datapipe import ShardedDataset
    from roko_tpu.datapipe.manifest import crosscheck_fingerprint

    nproc = jax.process_count()
    shards = dcfg.shards if dcfg.shards > 0 else max(1, nproc)
    shard_id = dcfg.shard_id if dcfg.shard_id >= 0 else jax.process_index()
    data_seed = dcfg.seed if dcfg.seed >= 0 else tcfg.seed
    if nproc > 1:
        # on a pod the shard topology IS the process topology: each
        # host feeds its own rows and the global batch is their
        # process-order concatenation (make_placer local_rows)
        if shards != nproc:
            raise ValueError(
                f"--data-shards {shards} on a {nproc}-process pod: "
                "shards must equal the process count (one shard per host)"
            )
        if shard_id != jax.process_index():
            raise ValueError(
                f"--data-shard-id {shard_id} conflicts with "
                f"jax.process_index()={jax.process_index()} on a pod; "
                "leave it at -1 (auto)"
            )
    if tcfg.batch_size % shards:
        raise ValueError(
            f"batch_size {tcfg.batch_size} not divisible by "
            f"{shards} data shards"
        )
    local_bs = tcfg.batch_size // shards
    model_batch = local_bs * (nproc if nproc > 1 else 1)
    if model_batch % dp:
        raise ValueError(
            f"per-step device batch {model_batch} (batch_size "
            f"{tcfg.batch_size} / {shards} shards) not divisible by dp={dp}"
        )

    train_ds = ShardedDataset(
        train_path,
        num_shards=shards,
        shard_id=shard_id,
        seed=data_seed,
        block_size=dcfg.block_size,
        prefetch_blocks=dcfg.input_prefetch,
        mix_blocks=dcfg.mix_blocks,
        preload=tcfg.in_memory,
        manifest_path=dcfg.manifest,
        log=log,
    )
    crosscheck_fingerprint(train_ds.manifest)  # no-op single process
    if shards > 1 and train_ds.num_blocks < 4 * shards:
        log(
            f"WARNING: only {train_ds.num_blocks} span block(s) for "
            f"{shards} data shards — shard balance is block-granular; "
            "lower --data-block-size (or grow the corpus) so every "
            "shard owns several blocks"
        )
    val_ds = (
        ShardedDataset(
            val_path,
            seed=data_seed,
            block_size=dcfg.block_size,
            prefetch_blocks=dcfg.input_prefetch,
            preload=tcfg.in_memory,
        )
        if val_path
        else None
    )
    if val_ds is not None:
        # hosts disagreeing on the VAL corpus would compute different
        # val_acc and take different early-stop/guard branches —
        # a pod deadlock, not a metric blip; refuse like the train path
        crosscheck_fingerprint(val_ds.manifest)
    holdout_ppm = 0
    if val_ds is None and tcfg.val_fraction > 0:
        # row-level seeded holdout, identical on every host; works for
        # both the preloaded and streaming backends (the split is index
        # arithmetic over the manifest, not a data copy). The fraction
        # shapes the train stream, so it is pinned in data_state.pipe
        # (parts-per-million — the pipe tree is int32).
        holdout_ppm = int(round(tcfg.val_fraction * 1e6))
        train_ds, val_ds = train_ds.split_holdout(tcfg.val_fraction, data_seed)
        log(
            f"held out {len(val_ds)} of {len(train_ds) + len(val_ds)} "
            "windows for validation (--val-fraction)"
        )
    log(
        f"train windows: {len(train_ds)}"
        + (f", val windows: {len(val_ds)}" if val_ds else " (no val set)")
        + (
            f" [shard {shard_id}/{shards}: {train_ds.local_rows()} local "
            f"rows, corpus {train_ds.manifest.fingerprint[:12]}]"
            if shards > 1
            else ""
        )
    )

    model = RokoModel(cfg.model)
    tx = optax.adam(tcfg.lr)
    root = jax.random.PRNGKey(tcfg.seed)
    init_rng, dropout_rng = jax.random.split(root)
    if tcfg.dropout_rng_impl != "threefry":
        # dropout-mask stream only (init stays threefry so params are
        # impl-independent); fold_in/split on this key inherit the impl
        dropout_rng = jax.random.key(
            tcfg.seed + 1, impl=tcfg.dropout_rng_impl
        )

    eval_step = make_eval_step(model, mesh)
    # the train stream feeds LOCAL shard rows (each host its own); the
    # eval path keeps the legacy identical-global-batch contract
    place = make_placer(mesh, local_rows=shards > 1)
    steps_per_epoch = max(1, train_ds.steps_per_epoch(local_bs))

    manager = ckpt_lib.CheckpointManager(
        out_dir, keep=tcfg.keep_checkpoints, log=log
    )
    guard = guard_lib.TrainGuard(gcfg, log) if gcfg.enabled else None

    if val_ds is None:
        # train-set accuracy is near-monotonic, so patience would never
        # fire — or fire on noise; run the full epoch budget instead
        # (VERDICT r2 weak #4)
        log("no val set: early stopping disabled, running all epochs")

    def _run(attempt: int) -> TrainState:
        # jitted steps are built per attempt — a fresh trace after a
        # rollback (rollbacks are rare; the recompile is noise next to
        # the restore) — and the dropout stream is re-jittered so a
        # transient mask-dependent fault doesn't replay identically
        if guard is not None:
            grad_step, apply_step = make_guarded_train_step(model, tx, mesh)
            train_step = None
        else:
            train_step = make_train_step(model, tx, mesh)

        state = create_state(model, tx, init_rng)
        state = TrainState(
            put_replicated(state.params, mesh),
            put_replicated(state.opt_state, mesh),
            state.step,
        )
        params, opt_state, step_no = state.params, state.opt_state, state.step
        best_acc, bad_epochs = -1.0, 0
        start_epoch, start_batch, start_applied = 0, 0, 0
        running0 = np.float32(0.0)
        persisted_rollbacks = 0

        # the saved state carries the epoch, early-stopping counters and
        # data position explicitly — deriving the epoch from
        # step // steps_per_epoch would break on resume with a different
        # batch size or dataset, and a resume that forgot
        # best_acc/bad_epochs would silently reset the patience window
        # (ADVICE r1 (b))
        full_template = dict(
            state.as_dict(),
            epoch=jnp.zeros((), jnp.int32),
            early_stop={
                "best_acc": jnp.zeros((), jnp.float32),
                "bad_epochs": jnp.zeros((), jnp.int32),
            },
            data_state={
                "epoch": jnp.zeros((), jnp.int32),
                "batch": jnp.zeros((), jnp.int32),
                "applied": jnp.zeros((), jnp.int32),
                "loss_sum": jnp.zeros((), jnp.float32),
                # sentinel stream state rides along so a killed-and-
                # resumed run makes the same skip/rollback decisions an
                # uninterrupted one would (guard.state_dict)
                "guard": {
                    "ema": jnp.zeros((), jnp.float32),
                    "var": jnp.zeros((), jnp.float32),
                    "good_steps": jnp.zeros((), jnp.int32),
                    "consecutive_bad": jnp.zeros((), jnp.int32),
                    "rollbacks": jnp.zeros((), jnp.int32),
                },
                # shard topology + corpus fingerprint the run was
                # trained on: a resume under a different sharding or a
                # mutated corpus would silently shift every stream, so
                # it refuses instead (datapipe manifest)
                "pipe": {
                    "shards": jnp.zeros((), jnp.int32),
                    "shard_id": jnp.zeros((), jnp.int32),
                    "seed": jnp.zeros((), jnp.int32),
                    "block_size": jnp.zeros((), jnp.int32),
                    "mix": jnp.zeros((), jnp.int32),
                    "local_bs": jnp.zeros((), jnp.int32),
                    "val_ppm": jnp.zeros((), jnp.int32),
                    "fp_hi": jnp.zeros((), jnp.int32),
                    "fp_lo": jnp.zeros((), jnp.int32),
                },
            },
        )
        if resume or attempt > 0:
            # the restore target is built per candidate from its actual
            # on-disk keys (older layouts lack 'epoch'/'early_stop'/
            # 'data_state'), and each candidate is verified against its
            # integrity manifest with fallback to the next older good
            # checkpoint (ADVICE r1 (a); checkpoint.py)
            restored = manager.restore_latest(template=full_template)
            if restored is not None:
                params = put_replicated(restored["params"], mesh)
                opt_state = put_replicated(restored["opt_state"], mesh)
                step_no = jnp.asarray(restored["step"], jnp.int32)
                if "data_state" in restored:
                    dstate = jax.device_get(restored["data_state"])
                    start_epoch = int(dstate["epoch"])
                    start_batch = int(dstate["batch"])
                    start_applied = int(dstate["applied"])
                    running0 = np.float32(dstate["loss_sum"])
                    gstate = dstate.get("guard")
                    if gstate is not None:
                        persisted_rollbacks = int(gstate["rollbacks"])
                        if guard is not None:
                            guard.load_state(gstate)
                    pstate = dstate.get("pipe")
                    if pstate is not None:
                        # refuse any change to the inputs the epoch
                        # stream is a pure function of: (fingerprint,
                        # shards, shard_id, seed, block_size, mix).
                        # shard_id is pinned only single-process: on a
                        # pod it EQUALS process_index (validated above)
                        # but differs per host, and the checkpoint's
                        # scalar bookkeeping is a replicated tree —
                        # persisting a per-host value there would make
                        # every non-primary host refuse its own resume.
                        fp_hi, fp_lo = train_ds.manifest.fingerprint32_pair()
                        keys = (
                            "shards", "shard_id", "seed", "block_size",
                            "mix", "local_bs", "val_ppm", "fp_hi", "fp_lo",
                        )
                        # the persisted position is denominated in
                        # LOCAL batches, so local_bs is pinned only for
                        # a MID-epoch resume (start_batch > 0) — a
                        # different batch size would land at the wrong
                        # sample. At an epoch boundary the position is
                        # 0 in any unit, and resuming with a new batch
                        # size is a supported, test-pinned workflow.
                        skip = (
                            frozenset() if start_batch > 0
                            else frozenset(("local_bs",))
                        )
                        cmp_keys = [
                            k for k in keys if k in pstate and k not in skip
                        ]
                        saved = tuple(int(pstate[k]) for k in cmp_keys)
                        now_all = dict(
                            shards=shards,
                            shard_id=shard_id if nproc == 1 else -1,
                            seed=data_seed,
                            block_size=dcfg.block_size,
                            mix=dcfg.mix_blocks,
                            local_bs=local_bs,
                            val_ppm=holdout_ppm,
                            fp_hi=fp_hi, fp_lo=fp_lo,
                        )
                        now = tuple(now_all[k] for k in cmp_keys)
                        if saved != now:
                            diff = ", ".join(
                                f"{k}: {s} -> {n}"
                                for k, s, n in zip(cmp_keys, saved, now)
                                if s != n
                            )
                            raise RuntimeError(
                                "refusing to resume: the data-stream "
                                f"spec changed since the checkpoint ({diff}"
                                "; fp = corpus fingerprint). The stream "
                                "would silently diverge from the trained "
                                "prefix — restore the original sharding/"
                                "seed/corpus or start fresh with "
                                "--no-resume."
                            )
                    elif start_batch > 0:
                        # pre-datapipe mid-epoch checkpoint: the epoch
                        # stream algorithm changed in this release, so
                        # the rest of THIS epoch rides a different
                        # shuffle than its trained prefix (coverage of
                        # later epochs is unaffected)
                        obs_events.emit(
                            "guard", "legacy_resume", log=log,
                            detail="pre-datapipe mid-epoch checkpoint; "
                            "the remainder of the current epoch replays "
                            "on the new input-pipeline shuffle, not the "
                            "one its prefix trained on",
                        )
                elif "epoch" in restored:
                    start_epoch = int(jax.device_get(restored["epoch"])) + 1
                else:  # pre-'epoch' layout: recover from the step count
                    start_epoch = int(restored["step"]) // steps_per_epoch
                if "early_stop" in restored:
                    es = jax.device_get(restored["early_stop"])
                    best_acc = float(es["best_acc"])
                    bad_epochs = int(es["bad_epochs"])
                log(
                    f"resumed from step {int(jax.device_get(step_no))} "
                    f"(epoch {start_epoch}, batch {start_batch}, "
                    f"best val_acc {best_acc:.5f}, "
                    f"{bad_epochs} stale epochs)"
                )
        # dropout-stream jitter = persisted rollback count + in-process
        # rollbacks: monotone across rollbacks (a transient fault replays
        # on a fresh mask stream) and stable across kill+resume (the
        # resumed process picks up the stream the killed attempt used)
        jitter = persisted_rollbacks + attempt
        drop_rng = (
            dropout_rng
            if jitter == 0
            else jax.random.fold_in(dropout_rng, jitter)
        )
        hstep = int(jax.device_get(step_no))

        def _guard_state():
            g = (
                guard.state_dict()
                if guard is not None
                else {
                    "ema": float("nan"),
                    "var": 0.0,
                    "good_steps": 0,
                    "consecutive_bad": 0,
                }
            )
            return {
                "ema": np.asarray(g["ema"], np.float32),
                "var": np.asarray(g["var"], np.float32),
                "good_steps": np.asarray(g["good_steps"], np.int32),
                "consecutive_bad": np.asarray(
                    g["consecutive_bad"], np.int32
                ),
                "rollbacks": np.asarray(jitter, np.int32),
            }

        def _pipe_state():
            # rides the REPLICATED scalar tree: every field must be
            # identical on all pod processes, so the per-host shard_id
            # is pinned only single-process (-1 = derived from
            # process_index, nothing to pin)
            fp_hi, fp_lo = train_ds.manifest.fingerprint32_pair()
            return {
                "shards": np.asarray(shards, np.int32),
                "shard_id": np.asarray(
                    shard_id if jax.process_count() == 1 else -1, np.int32
                ),
                "seed": np.asarray(data_seed, np.int32),
                "block_size": np.asarray(dcfg.block_size, np.int32),
                "mix": np.asarray(dcfg.mix_blocks, np.int32),
                "local_bs": np.asarray(local_bs, np.int32),
                "val_ppm": np.asarray(holdout_ppm, np.int32),
                "fp_hi": np.asarray(fp_hi, np.int32),
                "fp_lo": np.asarray(fp_lo, np.int32),
            }

        def _save_mid(epoch, n_batches, n_applied, running):
            # mid-epoch, latest-only checkpoint carrying the data
            # position; scalar bookkeeping must be globally-replicated
            # arrays (orbax refuses host-local jax.Arrays on a pod)
            extras = put_replicated(
                {
                    "step": np.asarray(hstep, np.int32),
                    # 'epoch' stays "last completed" for legacy readers
                    "epoch": np.asarray(epoch - 1, np.int32),
                    "early_stop": {
                        "best_acc": np.asarray(best_acc, np.float32),
                        "bad_epochs": np.asarray(bad_epochs, np.int32),
                    },
                    "data_state": {
                        "epoch": np.asarray(epoch, np.int32),
                        "batch": np.asarray(n_batches, np.int32),
                        "applied": np.asarray(n_applied, np.int32),
                        "loss_sum": np.asarray(
                            jax.device_get(running), np.float32
                        ),
                        "guard": _guard_state(),
                        "pipe": _pipe_state(),
                    },
                },
                mesh,
            )
            manager.save_latest(
                {"params": params, "opt_state": opt_state, **extras}
            )

        for epoch in range(start_epoch, tcfg.epochs):
            t0 = time.perf_counter()
            skip = start_batch if epoch == start_epoch else 0
            # sample-granular checkpointable iterator over this shard's
            # slice of the epoch stream: epoch E shuffles identically
            # whether or not the run was interrupted inside it (the
            # stream rng derives from (data seed, epoch) in
            # ShardedDataset.epoch_rng), and a mid-epoch resume
            # fast-forwards to batch `skip` in O(spans skipped) — no
            # prefix re-read. The trailing batch pads (zero-weight
            # rows) instead of dropping: fixed shapes for XLA, but
            # every window trains.
            batches = train_ds.iterator(
                epoch,
                local_bs,
                pad_to=local_bs,
                start_batch=skip,
            )
            # loss accumulates on device in f32 (one chain of adds in
            # batch order — the property the bit-identical resumed loss
            # curve rests on); without the guard there is ONE host
            # transfer per epoch so dispatch never blocks on a per-step
            # float()
            running = jnp.asarray(
                running0 if epoch == start_epoch else 0.0, jnp.float32
            )
            n_batches = skip
            n_applied = start_applied if epoch == start_epoch else 0
            # trace only the first trained epoch: a bounded window keeps
            # the profile loadable; a whole run would buffer every event
            trace = device_trace(trace_dir if epoch == start_epoch else None)
            with trace:
                for x, y, w in prefetch_to_device(batches, tcfg.prefetch, place):
                    if guard is None:
                        params, opt_state, loss, _ = train_step(
                            params, opt_state, step_no, x, y, w, drop_rng
                        )
                        running = running + loss
                        n_applied += 1
                    else:
                        # sentinel path: grads first (params untouched),
                        # decide on host, re-dispatch the update only
                        # for a good step — one host sync per step, the
                        # price of the guard (docs/TRAINING.md)
                        grads, loss, _, gfin = grad_step(
                            params, step_no, x, y, w, drop_rng
                        )
                        good = guard.check(
                            hstep,
                            float(jax.device_get(loss)),
                            bool(jax.device_get(gfin)),
                        )
                        if good:
                            params, opt_state, pfin = apply_step(
                                params, opt_state, grads
                            )
                            if not bool(jax.device_get(pfin)):
                                guard.params_nonfinite(hstep)
                            running = running + loss
                            n_applied += 1
                        else:
                            del grads  # skip: params/opt_state untouched
                    step_no = step_no + 1
                    hstep += 1
                    n_batches += 1
                    # in-epoch heartbeat: rate + ETA, no device sync (a
                    # float(loss) here would stall the dispatch queue)
                    if tcfg.log_every_steps and n_batches % tcfg.log_every_steps == 0:
                        dt_so_far = time.perf_counter() - t0
                        rate = (n_batches - skip) / max(dt_so_far, 1e-9)
                        eta = (steps_per_epoch - n_batches) / max(rate, 1e-9)
                        log(
                            f"  epoch {epoch} step {n_batches}/{steps_per_epoch} "
                            f"({rate * model_batch:.0f} windows/s, "
                            f"eta {eta:.0f}s)"
                        )
                    # (the epoch's final batch skips the mid save — the
                    # epoch-boundary manager.save moments later would
                    # immediately overwrite the same `latest` dir)
                    if (
                        gcfg.save_every_steps
                        and n_batches % gcfg.save_every_steps == 0
                        and n_batches < steps_per_epoch
                    ):
                        _save_mid(epoch, n_batches, n_applied, running)
                running_h = float(jax.device_get(running))
            dt = time.perf_counter() - t0

            # no-val fallback evaluates the FULL train corpus (an
            # unsharded view): every host must compute the identical
            # metric or early-stop/guard decisions would diverge
            eval_ds = val_ds if val_ds is not None else train_ds.unsharded()
            acc, vloss = evaluate(eval_step, params, eval_ds, tcfg.batch_size, mesh)
            guard_note = (
                f" [{guard.summary()}]"
                if guard is not None and guard.events
                else ""
            )
            log(
                f"epoch {epoch}: train_loss {running_h / max(n_applied,1):.4f} "
                f"val_acc {acc:.5f} val_loss {vloss:.4f} "
                f"({dt:.1f}s, {n_batches} steps, "
                f"{(n_batches - skip) * model_batch / max(dt, 1e-9):.0f} "
                f"windows/s)" + guard_note
            )

            # update the patience window BEFORE saving so a resumed run
            # restores the exact early-stopping state (ADVICE r1 (b))
            if acc > best_acc:
                best_acc, bad_epochs = acc, 0
            else:
                bad_epochs += 1

            # scalar bookkeeping must be globally-replicated arrays, not
            # host-local ones — orbax refuses host-local jax.Arrays in a
            # multi-host save
            extras = put_replicated(
                {
                    "step": np.asarray(hstep, np.int32),
                    "epoch": np.asarray(epoch, np.int32),
                    "early_stop": {
                        "best_acc": np.asarray(best_acc, np.float32),
                        "bad_epochs": np.asarray(bad_epochs, np.int32),
                    },
                    # epoch-boundary position: next epoch, batch 0 (the
                    # sentinel stream still carries across epochs)
                    "data_state": {
                        "epoch": np.asarray(epoch + 1, np.int32),
                        "batch": np.asarray(0, np.int32),
                        "applied": np.asarray(0, np.int32),
                        "loss_sum": np.asarray(0.0, np.float32),
                        "guard": _guard_state(),
                        "pipe": _pipe_state(),
                    },
                },
                mesh,
            )
            manager.save(
                hstep,
                {
                    "params": params,
                    "opt_state": opt_state,
                    **extras,
                },
                acc,
            )

            # early stopping, patience on val accuracy (ref:
            # roko/train.py:74-80); only meaningful with a real val set
            if val_ds is not None and bad_epochs >= tcfg.patience:
                log(f"early stop at epoch {epoch} (best val_acc {best_acc:.5f})")
                break
        if guard is not None and guard.events:
            log(guard.summary())
        return TrainState(params, opt_state, step_no)

    attempt = 0
    try:
        while True:
            try:
                return _run(attempt)
            except guard_lib.RollbackRequested as rb:
                if not manager.has_checkpoint():
                    raise RuntimeError(
                        f"guard requested rollback ({rb.reason} at step "
                        f"{rb.step}) but no checkpoint exists yet; cannot "
                        "recover a run that failed before its first save"
                    ) from rb
                guard.note_rollback()
                attempt += 1
                if attempt > gcfg.max_rollbacks:
                    raise RuntimeError(
                        f"giving up after {gcfg.max_rollbacks} rollbacks "
                        f"(last: {rb.reason} at step {rb.step}); the fault "
                        "replays deterministically — inspect the data/"
                        "config instead of rolling back again"
                    ) from rb
                obs_events.emit(
                    "guard", "rollback", log=log,
                    reason=rb.reason,
                    step=rb.step,
                    rollbacks=attempt,
                    max_rollbacks=gcfg.max_rollbacks,
                )
    finally:
        manager.close()
