"""Jitted, mesh-sharded train/eval steps and the epoch driver.

Replaces the reference's ignite Engine pair + callbacks (ref:
roko/train.py:41-111) with an explicit loop: Adam(1e-4), cross-entropy
over the 5 base classes at every one of the 90 window columns, per-epoch
validation accuracy, early stopping with patience 7, best-k Orbax
checkpoints (ref hyperparams: roko/train.py:12-15,39,74-84).

TPU mapping: params and optimizer state are replicated over the mesh,
batches are sharded over the ``dp`` axis (`PartitionSpec("dp")`), and the
gradient all-reduce is the `psum` XLA inserts for the replicated-output
jit — no hand-written collectives (SURVEY.md §2 north-star row "Data
parallel (training)").
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from roko_tpu.config import RokoConfig
from roko_tpu.models.model import RokoModel
from roko_tpu.parallel.mesh import (
    AXIS_DP,
    data_sharding,
    make_mesh,
    put_replicated,
    replicated_sharding,
)
from roko_tpu.training import checkpoint as ckpt_lib
from roko_tpu.training.data import InMemoryDataset, prefetch_to_device
from roko_tpu.utils.profiling import device_trace

Params = Dict[str, Any]


@dataclasses.dataclass
class TrainState:
    params: Params
    opt_state: Any
    step: jax.Array  # scalar int32

    def as_dict(self) -> Dict[str, Any]:
        return {"params": self.params, "opt_state": self.opt_state, "step": self.step}


def create_state(
    model: RokoModel, tx: optax.GradientTransformation, rng: jax.Array
) -> TrainState:
    params = model.init(rng)
    return TrainState(params, tx.init(params), jnp.zeros((), jnp.int32))


def _loss_and_stats(model, params, x, y, w, rng):
    """Mean CE over real rows + summed correct/total counts.

    ``w`` is a per-row weight (0 for padding rows) so fixed-shape sharded
    batches don't bias the metrics.
    """
    logits = model.apply(
        params, x, deterministic=rng is None, rng=rng
    )  # [B,90,5] f32
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, y[..., None].astype(jnp.int32), axis=-1)[..., 0]
    per_row = -ll.mean(axis=-1)  # [B] mean over 90 columns
    denom = jnp.maximum(w.sum(), 1.0)
    loss = (per_row * w).sum() / denom
    pred = jnp.argmax(logits, axis=-1)
    correct = ((pred == y) * w[:, None]).sum()
    total = w.sum() * y.shape[1]
    return loss, (correct, total)


def make_train_step(
    model: RokoModel, tx: optax.GradientTransformation, mesh: Mesh
) -> Callable:
    repl = replicated_sharding(mesh)
    data = data_sharding(mesh)

    # params/opt_state shardings are None: the step preserves whatever
    # placement the caller chose (replicated for the GRU family,
    # tensor-parallel NamedShardings from parallel/tp.py for the
    # transformer), so the same step function serves dp and dp+tp.
    @partial(
        jax.jit,
        in_shardings=(None, None, repl, data, data, data, repl),
        out_shardings=(None, None, repl, repl),
        donate_argnums=(0, 1),
    )
    def step(params, opt_state, step_no, x, y, w, rng):
        rng = jax.random.fold_in(rng, step_no)

        def loss_fn(p):
            loss, aux = _loss_and_stats(model, p, x, y, w, rng)
            return loss, aux

        (loss, (correct, total)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, correct / jnp.maximum(total, 1.0)

    return step


def make_eval_step(model: RokoModel, mesh: Mesh) -> Callable:
    repl = replicated_sharding(mesh)
    data = data_sharding(mesh)

    @partial(
        jax.jit,
        in_shardings=(None, data, data, data),
        out_shardings=(repl, repl, repl),
    )
    def step(params, x, y, w):
        loss, (correct, total) = _loss_and_stats(model, params, x, y, w, None)
        return loss, correct, total

    return step


def make_placer(mesh: Mesh) -> Callable:
    """Host->device placement for a (x, y, w)-style tuple of global
    batches, correct on multi-host pods.

    Single process: a plain ``device_put`` onto the dp sharding. With
    ``jax.process_count() > 1`` a host cannot ``device_put`` onto a mesh
    spanning non-addressable devices; instead every process slices its
    own rows out of the (identically generated) global batch and wraps
    them with ``jax.make_array_from_process_local_data``, which
    assembles the logically-global array from per-process shards
    (SURVEY.md §5.8; VERDICT r2 task #3). Row-slice <-> device locality
    holds because ``jax.devices()`` orders devices process-major and the
    mesh's dp axis follows that order."""
    sharding = data_sharding(mesh)
    nproc = jax.process_count()
    pid = jax.process_index()

    def place(batch):
        if nproc == 1:
            return tuple(jax.device_put(a, sharding) for a in batch)
        out = []
        for a in batch:
            if a.shape[0] % nproc:
                raise ValueError(
                    f"global batch {a.shape[0]} not divisible by "
                    f"{nproc} processes"
                )
            per = a.shape[0] // nproc
            local = a[pid * per : (pid + 1) * per]
            out.append(
                jax.make_array_from_process_local_data(
                    sharding, local, a.shape
                )
            )
        return tuple(out)

    return place


def evaluate(eval_step, params, dataset, batch_size, mesh) -> Tuple[float, float]:
    """Return (mean position accuracy, mean per-window loss)."""
    place = make_placer(mesh)

    correct = total = 0.0
    loss_sum = rows = 0.0
    it = dataset.batches(batch_size, pad_to=batch_size)
    for x, y, w in prefetch_to_device(it, 2, place):
        n_rows = float(w.sum())
        loss, c, t = eval_step(params, x, y, w)
        loss_sum += float(loss) * n_rows
        rows += n_rows
        correct += float(c)
        total += float(t)
    return correct / max(total, 1.0), loss_sum / max(rows, 1.0)


def _warn_if_cpu_mesh_oversubscribed(mesh: Mesh, log) -> None:
    """A virtual CPU mesh wider than the physical core count is a
    correctness hazard, not just slow: XLA CPU collectives require every
    participant's thread to reach the rendezvous within ~40s, and when
    the per-device shard computation itself takes tens of seconds the
    devices execute serially on the contended cores, so device 0 can
    wait out the timeout before device N-1 even starts — rendezvous.cc
    then F-aborts the process ("Expected N threads to join ... not all
    of them arrived"). Observed in r5 with the full model at dp=8 on a
    1-core host; tiny-model tests never trip it. Warn loudly so the
    user reaches for --dp 1 before the crash does."""
    import os

    n_mesh = int(np.prod(list(mesh.shape.values())))
    cores = os.cpu_count() or 1
    if n_mesh > 1 and cores < n_mesh and mesh.devices.flat[0].platform == "cpu":
        log(
            f"WARNING: {n_mesh}-device CPU mesh on {cores} core(s) — XLA "
            "CPU collectives can hit their rendezvous timeout and abort "
            "when per-device compute is heavy; use --dp 1 (or fewer "
            "devices than cores) for full-size models on small hosts"
        )


def train(
    cfg: RokoConfig,
    train_path: str,
    out_dir: str,
    val_path: Optional[str] = None,
    *,
    mesh: Optional[Mesh] = None,
    resume: bool = True,
    trace_dir: Optional[str] = None,
    log: Callable[[str], None] = print,
) -> TrainState:
    """Full training run; returns the final state. Best-k checkpoints by
    validation accuracy land in ``out_dir`` (ref flow: roko/train.py:18-111).

    Checkpoints carry optimizer state, step, epoch and the
    early-stopping counters, so an interrupted run resumes exactly (the
    reference had no resume at all, SURVEY.md §5.3-5.4).

    Multi-host pods: call-site needs nothing special — ``train()``
    initialises ``jax.distributed`` when a pod topology is detected, the
    mesh spans all hosts' devices, every process feeds its slice of the
    global batch (``make_placer``), logging is primary-only, and every
    process participates in checkpoint save/restore (the Orbax
    multi-host contract: process 0 writes metadata, all processes write
    their addressable shards — gating save on the primary would
    deadlock sharded arrays)."""
    from roko_tpu.parallel import distributed

    distributed.initialize()  # no-op single host (SURVEY §5.8)
    if not distributed.is_primary():
        log = lambda s: None  # noqa: E731 — primary-only logging
    tcfg = cfg.train
    mesh = mesh or make_mesh(cfg.mesh)
    dp = mesh.shape[AXIS_DP]
    if tcfg.batch_size % dp:
        raise ValueError(
            f"batch_size {tcfg.batch_size} not divisible by dp={dp}"
        )
    _warn_if_cpu_mesh_oversubscribed(mesh, log)

    if tcfg.in_memory:
        train_ds = InMemoryDataset.from_path(train_path)
    else:  # out-of-core streaming (ref lazy TrainDataset, SURVEY §2.7)
        from roko_tpu.training.lazy_data import StreamingDataset

        train_ds = StreamingDataset(train_path)
    val_ds = InMemoryDataset.from_path(val_path) if val_path else None
    if val_ds is None and tcfg.val_fraction > 0:
        if not tcfg.in_memory:
            raise ValueError(
                "--val-fraction needs the in-memory dataset (--memory); "
                "pass an explicit --val set for streaming runs"
            )
        train_ds, val_ds = train_ds.split_holdout(tcfg.val_fraction, tcfg.seed)
        log(
            f"held out {len(val_ds)} of {len(train_ds) + len(val_ds)} "
            "windows for validation (--val-fraction)"
        )
    log(
        f"train windows: {len(train_ds)}"
        + (f", val windows: {len(val_ds)}" if val_ds else " (no val set)")
    )

    model = RokoModel(cfg.model)
    tx = optax.adam(tcfg.lr)
    root = jax.random.PRNGKey(tcfg.seed)
    init_rng, dropout_rng = jax.random.split(root)
    if tcfg.dropout_rng_impl != "threefry":
        # dropout-mask stream only (init stays threefry so params are
        # impl-independent); fold_in/split on this key inherit the impl
        dropout_rng = jax.random.key(
            tcfg.seed + 1, impl=tcfg.dropout_rng_impl
        )
    state = create_state(model, tx, init_rng)
    state = TrainState(
        put_replicated(state.params, mesh),
        put_replicated(state.opt_state, mesh),
        state.step,
    )

    train_step = make_train_step(model, tx, mesh)
    eval_step = make_eval_step(model, mesh)
    place = make_placer(mesh)

    manager = ckpt_lib.CheckpointManager(out_dir, keep=tcfg.keep_checkpoints)
    best_acc, bad_epochs = -1.0, 0
    params, opt_state, step_no = state.params, state.opt_state, state.step

    # the saved state carries the epoch and early-stopping counters
    # explicitly — deriving the epoch from step // steps_per_epoch would
    # break on resume with a different batch size or dataset, and a
    # resume that forgot best_acc/bad_epochs would silently reset the
    # patience window (ADVICE r1 (b))
    full_template = dict(
        state.as_dict(),
        epoch=jnp.zeros((), jnp.int32),
        early_stop={
            "best_acc": jnp.zeros((), jnp.float32),
            "bad_epochs": jnp.zeros((), jnp.int32),
        },
    )
    start_epoch = 0
    if resume:
        # build the restore target from the checkpoint's actual on-disk
        # layout (older layouts lack 'epoch'/'early_stop') — a corrupt
        # checkpoint now raises instead of being mistaken for a legacy
        # layout (ADVICE r1 (a))
        keys = manager.latest_keys()
        if keys is not None:
            like = {k: v for k, v in full_template.items() if k in keys}
            restored = manager.restore_latest(like=like)
        else:
            restored = None
        if restored is not None:
            params = put_replicated(restored["params"], mesh)
            opt_state = put_replicated(restored["opt_state"], mesh)
            step_no = jnp.asarray(restored["step"], jnp.int32)
            if "epoch" in restored:
                start_epoch = int(jax.device_get(restored["epoch"])) + 1
            else:  # pre-'epoch' layout: recover from the step count
                steps_per_epoch = max(1, -(-len(train_ds) // tcfg.batch_size))
                start_epoch = int(restored["step"]) // steps_per_epoch
            if "early_stop" in restored:
                es = jax.device_get(restored["early_stop"])
                best_acc = float(es["best_acc"])
                bad_epochs = int(es["bad_epochs"])
            log(
                f"resumed from step {int(jax.device_get(step_no))} "
                f"(epoch {start_epoch}, best val_acc {best_acc:.5f}, "
                f"{bad_epochs} stale epochs)"
            )

    if val_ds is None:
        # train-set accuracy is near-monotonic, so patience would never
        # fire — or fire on noise; run the full epoch budget instead
        # (VERDICT r2 weak #4)
        log("no val set: early stopping disabled, running all epochs")

    steps_per_epoch = max(1, -(-len(train_ds) // tcfg.batch_size))
    try:
        for epoch in range(start_epoch, tcfg.epochs):
            t0 = time.perf_counter()
            # per-epoch derived RNG: epoch E shuffles identically whether
            # or not the run was interrupted before it, for both the
            # in-memory and streaming datasets (no replay bookkeeping)
            np_rng = np.random.default_rng(
                np.random.SeedSequence([tcfg.seed, epoch])
            )
            # pad the trailing batch (zero-weight rows) instead of dropping
            # it: fixed shapes for XLA, but every window trains (the
            # reference's DataLoader also kept the last partial batch)
            batches = train_ds.batches(
                tcfg.batch_size, rng=np_rng, pad_to=tcfg.batch_size
            )
            # loss accumulates on device; one host transfer per epoch so
            # dispatch never blocks on a per-step float()
            running = jnp.zeros((), jnp.float32)
            n_batches = 0
            # trace only the first trained epoch: a bounded window keeps
            # the profile loadable; a whole run would buffer every event
            trace = device_trace(trace_dir if epoch == start_epoch else None)
            with trace:
                for x, y, w in prefetch_to_device(batches, tcfg.prefetch, place):
                    params, opt_state, loss, _ = train_step(
                        params, opt_state, step_no, x, y, w, dropout_rng
                    )
                    step_no = step_no + 1
                    running = running + loss
                    n_batches += 1
                    # in-epoch heartbeat: rate + ETA, no device sync (a
                    # float(loss) here would stall the dispatch queue)
                    if tcfg.log_every_steps and n_batches % tcfg.log_every_steps == 0:
                        dt_so_far = time.perf_counter() - t0
                        rate = n_batches / max(dt_so_far, 1e-9)
                        eta = (steps_per_epoch - n_batches) / max(rate, 1e-9)
                        log(
                            f"  epoch {epoch} step {n_batches}/{steps_per_epoch} "
                            f"({rate * tcfg.batch_size:.0f} windows/s, "
                            f"eta {eta:.0f}s)"
                        )
                running = float(jax.device_get(running))
            dt = time.perf_counter() - t0

            eval_ds = val_ds if val_ds is not None else train_ds
            acc, vloss = evaluate(eval_step, params, eval_ds, tcfg.batch_size, mesh)
            log(
                f"epoch {epoch}: train_loss {running / max(n_batches,1):.4f} "
                f"val_acc {acc:.5f} val_loss {vloss:.4f} "
                f"({dt:.1f}s, {n_batches} steps, "
                f"{n_batches * tcfg.batch_size / max(dt, 1e-9):.0f} windows/s)"
            )

            # update the patience window BEFORE saving so a resumed run
            # restores the exact early-stopping state (ADVICE r1 (b))
            if acc > best_acc:
                best_acc, bad_epochs = acc, 0
            else:
                bad_epochs += 1

            # scalar bookkeeping must be globally-replicated arrays, not
            # host-local ones — orbax refuses host-local jax.Arrays in a
            # multi-host save
            extras = put_replicated(
                {
                    "step": np.asarray(jax.device_get(step_no), np.int32),
                    "epoch": np.asarray(epoch, np.int32),
                    "early_stop": {
                        "best_acc": np.asarray(best_acc, np.float32),
                        "bad_epochs": np.asarray(bad_epochs, np.int32),
                    },
                },
                mesh,
            )
            manager.save(
                int(jax.device_get(step_no)),
                {
                    "params": params,
                    "opt_state": opt_state,
                    **extras,
                },
                acc,
            )

            # early stopping, patience on val accuracy (ref:
            # roko/train.py:74-80); only meaningful with a real val set
            if val_ds is not None and bad_epochs >= tcfg.patience:
                log(f"early stop at epoch {epoch} (best val_acc {best_acc:.5f})")
                break
    finally:
        manager.close()

    return TrainState(params, opt_state, step_no)
