"""NaN/loss-spike sentinel for the train loop (docs/TRAINING.md
"Failure handling (training)").

A single non-finite loss or gradient silently poisons every parameter it
touches — Adam moments keep the NaN alive even if later batches are
clean — and a pathological batch can spike the loss hard enough to wreck
a mostly-converged run. The sentinel watches per-step host scalars the
guarded train step returns (loss, gradient-finiteness; see
``loop.make_guarded_train_step``) and decides BEFORE the optimizer
update is dispatched:

- non-finite loss/grads, or a loss further than ``spike_sigma`` EMA
  standard deviations above the loss EMA → the update is *skipped*
  (params and optimizer state untouched — donation-safe because the
  apply step is simply never dispatched);
- ``max_bad_steps`` consecutive skips → :class:`RollbackRequested`, and
  the epoch driver restores the last good checkpoint with a re-jittered
  dropout RNG stream (a transient fault replays differently; a
  deterministic one hits ``max_rollbacks`` and aborts loudly).

Every event is one structured ``ROKO_GUARD`` line (``event=skip``,
``event=rollback``, ``event=param_nonfinite``, plus the checkpoint
integrity chain's ``event=ckpt_corrupt`` from
``roko_tpu/training/checkpoint.py``) so a log scrape sees the whole
failure-handling story with one grep. The format (and the optional
``--event-log`` JSONL sink every line also lands in) lives in
:mod:`roko_tpu.obs.events` — docs/OBSERVABILITY.md. This module is
host-side only — the device-side flags are produced in ``loop.py``.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict

from roko_tpu.config import GuardConfig
from roko_tpu.obs import events

#: prefix of every structured sentinel/integrity log line
GUARD_PREFIX = events.legacy_prefix("guard")


def guard_line(event: str, **fields) -> str:
    """One structured log line: ``ROKO_GUARD event=... k=v ...``.
    Floats are compacted; key order follows the call site. (Formatting
    delegates to the shared event plane; this wrapper remains the
    training-local spelling.)"""
    return events.format_line("guard", event, fields)


class RollbackRequested(RuntimeError):
    """Raised by :class:`TrainGuard` when consecutive bad steps exhaust
    ``max_bad_steps`` (or an applied update produced non-finite params).
    The epoch driver catches it and rolls back to the last good
    checkpoint."""

    def __init__(self, reason: str, step: int):
        super().__init__(
            f"guard requested rollback at step {step} (reason: {reason})"
        )
        self.reason = reason
        self.step = step


class TrainGuard:
    """Host-side sentinel state: loss EMA + variance EMA, consecutive-bad
    counter, event counters, ROKO_GUARD logging.

    Decisions are pure functions of replicated device scalars every
    process sees identically, so on a multi-host pod all processes skip
    (or roll back) in lockstep without any extra collective.
    """

    def __init__(self, cfg: GuardConfig, log: Callable[[str], None] = print):
        self.cfg = cfg
        self._log = log
        self.ema: float | None = None
        self.var = 0.0
        self.good_steps = 0
        self.consecutive_bad = 0
        self.counters: Dict[str, int] = {
            "skipped_nonfinite": 0,
            "skipped_spike": 0,
            "param_nonfinite": 0,
            "rollbacks": 0,
        }

    # -- decision --------------------------------------------------------

    def spike_threshold(self) -> float | None:
        """Loss level above which a step is a spike, or None while the
        EMA is still warming up. The variance EMA starts at zero and
        with decay beta has only accumulated ``1 - beta^n`` of the true
        variance after n updates — without the Adam-style bias
        correction an early threshold would sit ~sqrt(1-beta^n) too
        tight and flag ordinary noise as spikes."""
        if self.ema is None or self.good_steps < self.cfg.warmup_steps:
            return None
        updates = max(self.good_steps - 1, 1)  # first good step sets ema only
        bias = max(1.0 - self.cfg.ema_beta ** updates, 1e-12)
        return self.ema + self.cfg.spike_sigma * max(
            math.sqrt(self.var / bias), 1e-8
        )

    def check(self, step: int, loss: float, grads_finite: bool) -> bool:
        """Classify one step. Returns True when the update should be
        applied; False to skip it. Raises :class:`RollbackRequested`
        after ``max_bad_steps`` consecutive skips."""
        reason = None
        if not grads_finite or not math.isfinite(loss):
            reason = "nonfinite"
        else:
            threshold = self.spike_threshold()
            if threshold is not None and loss > threshold:
                reason = "spike"
        if reason is None:
            if self.ema is None:
                self.ema = loss
            else:
                beta = self.cfg.ema_beta
                prev = self.ema
                self.ema = beta * prev + (1.0 - beta) * loss
                self.var = beta * self.var + (1.0 - beta) * (loss - prev) ** 2
            self.good_steps += 1
            self.consecutive_bad = 0
            return True

        self.consecutive_bad += 1
        self.counters[f"skipped_{reason}"] += 1
        events.emit(
            "guard", "skip", log=self._log,
            reason=reason,
            step=step,
            loss=loss,
            ema=self.ema if self.ema is not None else float("nan"),
            consecutive=self.consecutive_bad,
            max_bad_steps=self.cfg.max_bad_steps,
        )
        if self.consecutive_bad >= self.cfg.max_bad_steps:
            raise RollbackRequested(reason, step)
        return False

    def params_nonfinite(self, step: int) -> None:
        """An APPLIED update produced non-finite params (overflow in the
        optimizer math despite finite grads). The old params were donated
        — skipping cannot help, so this rolls back immediately."""
        self.counters["param_nonfinite"] += 1
        events.emit(
            "guard", "param_nonfinite", log=self._log,
            step=step, action="rollback",
        )
        raise RollbackRequested("param_nonfinite", step)

    # -- checkpoint round-trip ------------------------------------------

    def state_dict(self) -> Dict[str, Any]:
        """Sentinel stream state for the checkpoint's ``data_state`` —
        a killed-and-resumed run must make the SAME skip/rollback
        decisions an uninterrupted one would (EMA armed at the same
        step, consecutive-bad count surviving a kill between bad
        steps). Event counters are per-process reporting and are not
        persisted. Floats are stored as f32, so decisions are
        resume-stable to f32 precision of the thresholds."""
        return {
            "ema": self.ema if self.ema is not None else float("nan"),
            "var": self.var,
            "good_steps": self.good_steps,
            "consecutive_bad": self.consecutive_bad,
        }

    def load_state(self, state: Dict[str, Any]) -> None:
        ema = float(state["ema"])
        self.ema = None if math.isnan(ema) else ema
        self.var = float(state["var"])
        self.good_steps = int(state["good_steps"])
        self.consecutive_bad = int(state["consecutive_bad"])

    # -- rollback bookkeeping -------------------------------------------

    def note_rollback(self) -> None:
        """Reset per-stream state after the driver rolled back: the EMA
        restarts from the restored trajectory (mixing pre-fault history
        into post-restore losses would mis-arm the spike detector)."""
        self.counters["rollbacks"] += 1
        self.consecutive_bad = 0
        self.ema = None
        self.var = 0.0
        self.good_steps = 0

    # -- reporting -------------------------------------------------------

    @property
    def skipped(self) -> int:
        return (
            self.counters["skipped_nonfinite"] + self.counters["skipped_spike"]
        )

    @property
    def events(self) -> int:
        return self.skipped + self.counters["param_nonfinite"] + self.counters[
            "rollbacks"
        ]

    def summary(self) -> str:
        c = self.counters
        return (
            f"guard: skipped={self.skipped} "
            f"(nonfinite={c['skipped_nonfinite']} spike={c['skipped_spike']}) "
            f"param_nonfinite={c['param_nonfinite']} "
            f"rollbacks={c['rollbacks']}"
        )
