"""Host-side training data pipeline.

Replaces torch `DataLoader(shuffle=True, num_workers=t)` (ref:
roko/train.py:30-32): examples live in host RAM as one uint8 ndarray
(the full Zymo 5-species train set is ~5 GB — comfortably host-resident),
an epoch is a seeded permutation, and a background thread keeps
`prefetch` batches ahead of the device so the TPU never waits on the
host. No worker processes: the transfer is one `device_put` of an
already-sliced contiguous array per batch.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional, Tuple

import jax
import numpy as np

from roko_tpu.data.hdf5 import load_training_arrays


class InMemoryDataset:
    """Flat (X, Y) arrays in host RAM (ref: InMemoryTrainDataset,
    roko/datasets.py:82-119)."""

    def __init__(self, X: np.ndarray, Y: np.ndarray):
        assert len(X) == len(Y)
        self.X = np.ascontiguousarray(X, dtype=np.uint8)
        self.Y = np.ascontiguousarray(Y, dtype=np.int32)

    @staticmethod
    def from_path(path: str) -> "InMemoryDataset":
        X, Y = load_training_arrays(path)
        return InMemoryDataset(X, Y)

    def __len__(self) -> int:
        return len(self.X)

    def split_holdout(
        self, fraction: float, seed: int
    ) -> Tuple["InMemoryDataset", "InMemoryDataset"]:
        """Deterministic (train, val) split: a seeded permutation holds
        out ``max(1, round(fraction * N))`` windows. Used when training
        without an explicit --val set but with --val-fraction, so early
        stopping has an honest metric (VERDICT r2 task #6)."""
        if not 0.0 < fraction < 1.0:
            raise ValueError(f"val fraction must be in (0, 1), got {fraction}")
        n = len(self)
        n_val = max(1, round(fraction * n))
        if n_val >= n:
            raise ValueError(
                f"val fraction {fraction} leaves no training windows (N={n})"
            )
        perm = np.random.default_rng(seed).permutation(n)
        val_idx, train_idx = perm[:n_val], perm[n_val:]
        return (
            InMemoryDataset(self.X[train_idx], self.Y[train_idx]),
            InMemoryDataset(self.X[val_idx], self.Y[val_idx]),
        )

    def batches(
        self,
        batch_size: int,
        *,
        rng: Optional[np.random.Generator] = None,
        drop_remainder: bool = False,
        pad_to: Optional[int] = None,
        skip_batches: int = 0,
    ) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Yield (x, y, weight) host batches.

        ``weight`` is 1.0 for real rows, 0.0 for padding rows added to
        reach ``pad_to`` (so sharded eval can use fixed batch shapes
        without biasing metrics).

        ``skip_batches`` fast-forwards past the first k batches of the
        SAME epoch stream — step-granular resume replays an interrupted
        epoch from exactly the next untrained batch (docs/TRAINING.md).

        Delegates to the sharded input engine
        (``roko_tpu/datapipe/engine.py``) over in-RAM spans cut at the
        datapipe block size: block permutation + per-block row
        permutations, so the epoch stream semantics match the
        manifest-backed :class:`roko_tpu.datapipe.ShardedDataset`,
        fast-forward is index arithmetic, and at most ~a block of
        fancy-indexed rows is materialised at a time (a corpus-sized
        ``X[order]`` copy would double peak host RAM for the multi-GB
        flagship corpus this class exists for).
        """
        from roko_tpu.datapipe.engine import iter_span_batches
        from roko_tpu.datapipe.manifest import DEFAULT_BLOCK_SIZE

        n = len(self)
        starts = list(range(0, n, DEFAULT_BLOCK_SIZE))
        counts = [min(DEFAULT_BLOCK_SIZE, n - s) for s in starts]

        def read_rows(b: int, order: np.ndarray):
            sel = starts[b] + order
            return self.X[sel], self.Y[sel]

        yield from iter_span_batches(
            counts,
            read_rows,
            batch_size,
            rng=rng,
            drop_remainder=drop_remainder,
            pad_to=pad_to,
            skip_batches=skip_batches,
        )


def prefetch_to_device(iterator, size: int, place) -> Iterator:
    """Run ``place`` (host batch -> device arrays) in a producer thread,
    keeping up to ``size`` batches in flight. JAX dispatch is async, so
    overlapping the host slice + device_put of batch N+1 with compute of
    batch N is all the pipelining the single-host case needs (the
    reference used DataLoader worker processes for the same purpose,
    roko/train.py:30)."""
    if size <= 0:
        for item in iterator:
            yield place(item)
        return

    q: "queue.Queue" = queue.Queue(maxsize=size)
    _END = object()
    stop = threading.Event()

    def _put(item) -> bool:
        """Bounded put that gives up when the consumer is gone, so an
        abandoned generator can't pin device batches forever."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for item in iterator:
                if not _put(place(item)):
                    return
        except BaseException as e:  # surface errors on the consumer side
            _put(e)
            return
        _put(_END)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        while not q.empty():  # unblock the producer and drop its buffers
            try:
                q.get_nowait()
            except queue.Empty:
                break
