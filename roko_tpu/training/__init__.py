"""Training harness: explicit JAX train loop replacing the reference's
pytorch-ignite engines (ref: roko/train.py).

- `roko_tpu.training.data` — host-side batch pipeline (shuffle, batch,
  double-buffered device prefetch).
- `roko_tpu.training.loop` — jitted train/eval steps sharded over the
  device mesh, epoch driver, early stopping.
- `roko_tpu.training.checkpoint` — Orbax checkpoints carrying params,
  optimizer state, step and the data-pipeline position, with a sha256
  integrity chain (committed manifests, verified fallback restore — the
  reference kept best-model params only, SURVEY.md §5.4).
- `roko_tpu.training.guard` — NaN/loss-spike sentinel: skip bad
  updates, roll back to the last good checkpoint after consecutive bad
  steps (docs/TRAINING.md "Failure handling").
"""

from roko_tpu.training.guard import TrainGuard
from roko_tpu.training.loop import TrainState, train

__all__ = ["train", "TrainState", "TrainGuard"]
