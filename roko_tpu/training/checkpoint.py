"""Orbax checkpointing with an integrity chain.

The reference checkpoints model params only, keyed by validation accuracy
(ignite ModelCheckpoint, ref: roko/train.py:82-84) — no optimizer state,
no resume. Here every checkpoint carries ``{params, opt_state, step}``
plus the val-accuracy metric, the manager keeps the best-k by val_acc,
and ``restore_latest``/``restore_best`` give both resume-from-step and
best-model-for-inference (SURVEY.md §5.3-5.4 build notes).

Integrity chain (docs/TRAINING.md "Failure handling"): every save
commits a ``roko_manifest.json`` — a sha256 per leaf file plus a digest
of the whole tree — ATOMICALLY (tmp + rename) after the orbax write
finishes, so a SIGKILL mid-save leaves a checkpoint *without* a
committed manifest rather than a silently-truncated one. Restore walks
the candidates newest-first (``latest``, then numbered steps), verifies
each manifest, logs a loud ``ROKO_GUARD`` line on corruption, and falls
back to the next older good checkpoint. When checkpoints exist on disk
but none verifies, restore raises :class:`CheckpointIntegrityError`
instead of silently training from scratch.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import orbax.checkpoint as ocp

from roko_tpu.obs import events as obs_events

#: committed last, atomically — its presence IS the commit record
MANIFEST_NAME = "roko_manifest.json"


class CheckpointIntegrityError(RuntimeError):
    """No checkpoint on disk passes manifest verification; refusing to
    silently start from scratch over existing (corrupt) state."""


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _manifest_entries(ckpt_dir: str) -> Dict[str, Dict[str, Any]]:
    """``relpath -> {sha256, bytes}`` for every file under ``ckpt_dir``
    except the manifest itself."""
    entries: Dict[str, Dict[str, Any]] = {}
    for dirpath, dirnames, filenames in os.walk(ckpt_dir):
        dirnames.sort()
        for name in sorted(filenames):
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, ckpt_dir)
            if rel == MANIFEST_NAME:
                continue
            entries[rel] = {
                "sha256": _sha256_file(path),
                "bytes": os.path.getsize(path),
            }
    return entries


def _tree_digest(entries: Dict[str, Dict[str, Any]]) -> str:
    """Structure digest: file set + per-file hashes, order-independent."""
    lines = [f"{rel}:{entries[rel]['sha256']}" for rel in sorted(entries)]
    return hashlib.sha256("\n".join(lines).encode()).hexdigest()


def write_manifest(ckpt_dir: str) -> str:
    """Hash every leaf file under ``ckpt_dir`` and commit the manifest
    atomically (write tmp, fsync, rename). Returns the manifest path.
    Call only after the checkpoint write has fully finished."""
    entries = _manifest_entries(ckpt_dir)
    manifest = {
        "version": 1,
        "tree_digest": _tree_digest(entries),
        "files": entries,
    }
    path = os.path.join(ckpt_dir, MANIFEST_NAME)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    # fsync the directory so the rename itself survives a crash
    dir_fd = os.open(ckpt_dir, os.O_RDONLY)
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)
    return path


def verify_manifest(ckpt_dir: str) -> Tuple[str, str]:
    """Verify ``ckpt_dir`` against its committed manifest.

    Returns ``(status, detail)`` with status one of:

    - ``"ok"``         — manifest present, every file matches;
    - ``"corrupt"``    — manifest unreadable, a file is missing,
      truncated, or its hash mismatches (detail names the first);
    - ``"unverified"`` — no manifest (pre-integrity legacy layout, or a
      save that was killed before commit).
    """
    path = os.path.join(ckpt_dir, MANIFEST_NAME)
    if not os.path.exists(path):
        return "unverified", "no manifest"
    try:
        with open(path) as f:
            manifest = json.load(f)
        files = manifest["files"]
        digest = manifest["tree_digest"]
    except (OSError, ValueError, KeyError) as e:
        return "corrupt", f"unreadable manifest ({e})"
    if _tree_digest(files) != digest:
        return "corrupt", "manifest tree digest mismatch"
    for rel, want in sorted(files.items()):
        fpath = os.path.join(ckpt_dir, rel)
        if not os.path.exists(fpath):
            return "corrupt", f"missing file {rel}"
        size = os.path.getsize(fpath)
        if size != want["bytes"]:
            return (
                "corrupt",
                f"truncated file {rel} ({size} != {want['bytes']} bytes)",
            )
        if _sha256_file(fpath) != want["sha256"]:
            return "corrupt", f"sha256 mismatch on {rel}"
    return "ok", f"{len(files)} files verified"


def _default_log(msg: str) -> None:
    import sys

    print(msg, file=sys.stderr)


class CheckpointManager:
    """Best-k checkpoints by val_acc PLUS an always-current ``latest``.

    The best-k manager prunes by metric only — with no latest-step
    exemption, a long post-peak plateau would leave resume pointing at
    a checkpoint many epochs old. ``save`` therefore also overwrites a
    standalone ``latest`` checkpoint every call; ``restore_latest``
    prefers it.

    Every save commits a sha256 manifest after the orbax write;
    ``restore_latest`` verifies candidates newest-first and falls back
    along the chain on corruption (module docstring).
    """

    def __init__(
        self,
        directory: str,
        keep: int = 3,
        log: Optional[Callable[[str], None]] = None,
    ):
        self.directory = os.path.abspath(directory)
        self._log = log if log is not None else _default_log
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep,
                best_fn=lambda m: m["val_acc"],
                best_mode="max",
                # the makedirs above already created the root; letting
                # orbax create it would run a sync_global_processes
                # barrier that needs psum collectives — unavailable on
                # the CPU backend's multi-process mode (pods on CPU are
                # a test configuration, tests/test_multiprocess.py)
                create=False,
            ),
        )
        self._ckptr = ocp.StandardCheckpointer()

    @property
    def _latest_path(self) -> str:
        return os.path.join(self.directory, "latest")

    def _step_path(self, step: int) -> str:
        return os.path.join(self.directory, str(step))

    def save(self, step: int, state: Dict[str, Any], val_acc: float) -> None:
        """Full save: best-k step + the always-current ``latest``, both
        with committed manifests. Synchronous — the integrity chain
        hashes the files, so the orbax write must have finished."""
        self._mgr.save(
            step,
            args=ocp.args.StandardSave(state),
            metrics={"val_acc": float(val_acc)},
        )
        self._ckptr.save(self._latest_path, state, force=True)
        self.wait()
        self._commit_manifests([self._step_path(step), self._latest_path])

    def save_latest(self, state: Dict[str, Any]) -> None:
        """Mid-epoch save: overwrite ``latest`` only (no best-k entry —
        there is no val metric mid-epoch) and commit its manifest. Used
        for the step-granular checkpoint cadence
        (``GuardConfig.save_every_steps``)."""
        self._ckptr.save(self._latest_path, state, force=True)
        self._ckptr.wait_until_finished()
        self._commit_manifests([self._latest_path])

    def _commit_manifests(self, paths) -> None:
        """Write+commit a manifest per checkpoint dir. Primary-only on
        multi-host (every process writes its shards, but two writers of
        one manifest would race); a dir the best-k pruner already
        deleted is skipped. Kept as a separate seam so fault-injection
        tests can SIGKILL between the orbax write and the commit."""
        if jax.process_index() != 0:
            return
        for path in paths:
            if os.path.isdir(path):
                write_manifest(path)

    def wait(self) -> None:
        self._mgr.wait_until_finished()
        self._ckptr.wait_until_finished()

    # -- verified restore chain ------------------------------------------

    def _candidates(self) -> List[Tuple[Union[str, int], str]]:
        """Restore candidates newest-first: ``latest`` (overwritten on
        every save), then numbered best-k steps descending."""
        out: List[Tuple[Union[str, int], str]] = []
        if os.path.exists(self._latest_path):
            out.append(("latest", self._latest_path))
        steps = self._mgr.all_steps() or []
        for step in sorted(steps, reverse=True):
            out.append((int(step), self._step_path(int(step))))
        return out

    def _tree_at(self, name: Union[str, int]):
        """One candidate checkpoint's on-disk key STRUCTURE — a nested
        mapping (leaf values are metadata/arrays, only the keys
        matter). Used to filter a restore template per candidate, at
        every nesting level: newer layouts add nested bookkeeping (e.g.
        ``data_state.pipe``), and a target naming keys a candidate
        doesn't have makes orbax refuse the whole restore."""
        if name == "latest":
            self._ckptr.wait_until_finished()
            meta = self._ckptr.metadata(self._latest_path)
        else:
            meta = self._mgr.item_metadata(int(name))
        # orbax < 0.7 wrapped the tree (meta.item_metadata.tree); 0.7
        # returns the metadata tree itself as a plain dict. Two separate
        # getattr steps: the fallback at each level must be the value
        # from the level above, not the original wrapper, or an
        # item_metadata-without-tree shape resolves back to the wrapper
        # and .keys() explodes
        inner = getattr(meta, "item_metadata", meta)
        tree = getattr(inner, "tree", inner)
        if tree is None:
            # orbax 0.7 fresh-manager quirk: a manager that has never
            # SAVED in this process has no metadata handler for the
            # step's "default" item and returns an empty wrapper (the
            # metadata analogue of the targetless-restore KeyError).
            # Fall back to a targetless restore purely for the key
            # structure — only the fallback-to-numbered-step path pays
            # the extra read, and only on a fresh process.
            return self._restore_at(name, None)
        return tree

    @staticmethod
    def _filter_template(template, tree):
        """Recursively drop template keys the candidate doesn't have
        (at ANY depth), so older checkpoint layouts restore without
        guessing — the nested analogue of the top-level key filtering
        ADVICE r1 (a) introduced."""
        out = {}
        for k, v in template.items():
            try:
                sub = tree[k]
            except (KeyError, TypeError):
                continue
            if isinstance(v, dict):
                out[k] = CheckpointManager._filter_template(
                    v, sub if hasattr(sub, "__getitem__") else {}
                )
            else:
                out[k] = v
        return out

    def _restore_at(self, name: Union[str, int], like):
        if name == "latest":
            self._ckptr.wait_until_finished()
            if like is not None:
                target = jax.tree.map(ocp.utils.to_shape_dtype_struct, like)
                return self._ckptr.restore(self._latest_path, target)
            return self._ckptr.restore(self._latest_path)
        if like is not None:
            target = jax.tree.map(ocp.utils.to_shape_dtype_struct, like)
            return self._mgr.restore(
                int(name), args=ocp.args.StandardRestore(target)
            )
        # targetless restore still needs explicit args: a FRESH manager
        # (load_params opens one per call) has no handler registered for
        # the "default" item and a bare restore() raises KeyError on
        # orbax >= 0.7 (the registry is only populated by a save)
        return self._mgr.restore(int(name), args=ocp.args.StandardRestore())

    def restore_latest(
        self, like=None, *, template: Optional[Dict[str, Any]] = None
    ) -> Optional[Dict[str, Any]]:
        """Restore the newest checkpoint that VERIFIES, walking the
        fallback chain (``latest``, then numbered steps newest-first)
        past corrupt or uncommitted candidates with a loud ``ROKO_GUARD``
        line per skip.

        ``like`` is a fixed restore target used as-is for every
        candidate. ``template`` is a superset target filtered per
        candidate to its actual on-disk keys — resume uses it so older
        layouts restore without guessing (ADVICE r1 (a)).

        Raises :class:`CheckpointIntegrityError` when checkpoints exist
        but none verifies — never a silent fresh start. Candidates
        without a manifest are accepted (legacy layout) unless some
        OTHER checkpoint in the directory has one, in which case the
        missing manifest means an uncommitted (killed mid-save) write.
        """
        cands = self._candidates()
        uses_manifests = any(
            os.path.exists(os.path.join(p, MANIFEST_NAME)) for _, p in cands
        )
        for name, path in cands:
            status, detail = verify_manifest(path)
            if status == "corrupt" or (
                status == "unverified" and uses_manifests
            ):
                obs_events.emit(
                    "guard", "ckpt_corrupt", log=self._log,
                    checkpoint=path, detail=repr(detail), action="fallback",
                )
                continue
            cand_like = like
            if template is not None:
                cand_like = self._filter_template(
                    template, self._tree_at(name)
                )
            try:
                return self._restore_at(name, cand_like)
            except Exception as e:  # restore blew up on a "verified" dir
                obs_events.emit(
                    "guard", "ckpt_restore_failed", log=self._log,
                    checkpoint=path, error=repr(e), action="fallback",
                )
                continue
        if cands:
            raise CheckpointIntegrityError(
                f"checkpoints exist under {self.directory} but none "
                "verifies/restores; refusing to silently train from "
                "scratch over corrupt state (inspect or delete the "
                "directory to restart)"
            )
        return None

    def restore_best(self, like=None) -> Optional[Dict[str, Any]]:
        step = self._mgr.best_step()
        if step is None:
            return None
        path = self._step_path(int(step))
        status, detail = verify_manifest(path)
        if status == "unverified" and any(
            os.path.exists(os.path.join(p, MANIFEST_NAME))
            for _, p in self._candidates()
        ):
            # same rule as restore_latest: no manifest in a directory
            # where siblings have one means the commit was interrupted —
            # the best-k artifact ships to inference, so refuse loudly
            # rather than restore an unchecked write
            status, detail = "corrupt", "no committed manifest"
        if status == "corrupt":
            raise CheckpointIntegrityError(
                f"best checkpoint {path} fails verification ({detail})"
            )
        return self._restore_at(int(step), like)

    def latest_keys(self) -> Optional[set]:
        """Top-level key names of the most recent checkpoint (the
        ``latest`` dir if present, else the newest numbered step), or
        None when no checkpoint exists."""
        cands = self._candidates()
        if not cands:
            return None
        return set(self._tree_at(cands[0][0]).keys())

    def has_checkpoint(self) -> bool:
        return bool(self._candidates())

    def best_step(self) -> Optional[int]:
        return self._mgr.best_step()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._ckptr.wait_until_finished()
        self._mgr.close()
        self._ckptr.close()


def _tuplify(tree: Any) -> Any:
    """Restore the model-init pytree structure: orbax round-trips
    tuples (the per-layer GRU stack) as lists, and an AOT executable
    (roko_tpu/compile/bundle.py) compiled against the init structure
    refuses a list-shaped pytree as a different program. Params hold
    only dicts/tuples of arrays, so list -> tuple is exact."""
    if isinstance(tree, dict):
        return {k: _tuplify(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return tuple(_tuplify(v) for v in tree)
    return tree


def load_params(path: str) -> Dict[str, Any]:
    """Load params from a checkpoint directory (best step, falling back
    to ``latest`` when no best-k step exists — e.g. a dir holding only
    the always-current ``latest``, ADVICE r1 (c)) or a single
    saved-state dir; returns the params pytree (tuple-canonical, the
    structure ``model.init`` produces — see :func:`_tuplify`)."""
    path = os.path.abspath(path)
    if os.path.isdir(path):
        entries = os.listdir(path)
        has_steps = any(name.isdigit() for name in entries)
        if has_steps or "latest" in entries:
            mgr = CheckpointManager(path)
            try:
                state = mgr.restore_best() if has_steps else None
                if state is None:
                    state = mgr.restore_latest()
            finally:
                mgr.close()
            if state is None:
                raise FileNotFoundError(f"no checkpoints under {path}")
            return _tuplify(state["params"])
    status, detail = verify_manifest(path)
    if status == "corrupt":
        raise CheckpointIntegrityError(
            f"saved state {path} fails verification ({detail})"
        )
    ckptr = ocp.StandardCheckpointer()
    state = ckptr.restore(path)
    return _tuplify(state["params"] if "params" in state else state)


def save_params(path: str, params: Dict[str, Any]) -> None:
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), {"params": params})
    ckptr.wait_until_finished()
    if jax.process_index() == 0:
        write_manifest(os.path.abspath(path))
