"""Orbax checkpointing.

The reference checkpoints model params only, keyed by validation accuracy
(ignite ModelCheckpoint, ref: roko/train.py:82-84) — no optimizer state,
no resume. Here every checkpoint carries ``{params, opt_state, step}``
plus the val-accuracy metric, the manager keeps the best-k by val_acc,
and ``restore_latest``/``restore_best`` give both resume-from-step and
best-model-for-inference (SURVEY.md §5.3-5.4 build notes).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import jax
import orbax.checkpoint as ocp


class CheckpointManager:
    """Best-k checkpoints by val_acc PLUS an always-current ``latest``.

    The best-k manager prunes by metric only — with no latest-step
    exemption, a long post-peak plateau would leave resume pointing at
    a checkpoint many epochs old. ``save`` therefore also overwrites a
    standalone ``latest`` checkpoint every call; ``restore_latest``
    prefers it.
    """

    def __init__(self, directory: str, keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep,
                best_fn=lambda m: m["val_acc"],
                best_mode="max",
                # the makedirs above already created the root; letting
                # orbax create it would run a sync_global_processes
                # barrier that needs psum collectives — unavailable on
                # the CPU backend's multi-process mode (pods on CPU are
                # a test configuration, tests/test_multiprocess.py)
                create=False,
            ),
        )
        self._ckptr = ocp.StandardCheckpointer()

    @property
    def _latest_path(self) -> str:
        return os.path.join(self.directory, "latest")

    def save(self, step: int, state: Dict[str, Any], val_acc: float) -> None:
        self._mgr.save(
            step,
            args=ocp.args.StandardSave(state),
            metrics={"val_acc": float(val_acc)},
        )
        self._ckptr.save(self._latest_path, state, force=True)

    def wait(self) -> None:
        self._mgr.wait_until_finished()
        self._ckptr.wait_until_finished()

    def _restore(self, step: Optional[int], like: Optional[Dict[str, Any]]):
        if step is None:
            return None
        if like is not None:
            target = jax.tree.map(ocp.utils.to_shape_dtype_struct, like)
            return self._mgr.restore(step, args=ocp.args.StandardRestore(target))
        # targetless restore still needs explicit args: a FRESH manager
        # (load_params opens one per call) has no handler registered for
        # the "default" item and a bare restore() raises KeyError on
        # orbax >= 0.7 (the registry is only populated by a save)
        return self._mgr.restore(step, args=ocp.args.StandardRestore())

    def restore_latest(self, like=None) -> Optional[Dict[str, Any]]:
        if os.path.exists(self._latest_path):
            self._ckptr.wait_until_finished()
            if like is not None:
                target = jax.tree.map(ocp.utils.to_shape_dtype_struct, like)
                return self._ckptr.restore(self._latest_path, target)
            return self._ckptr.restore(self._latest_path)
        return self._restore(self._mgr.latest_step(), like)

    def restore_best(self, like=None) -> Optional[Dict[str, Any]]:
        return self._restore(self._mgr.best_step(), like)

    def latest_keys(self) -> Optional[set]:
        """Top-level key names of the most recent checkpoint (the
        ``latest`` dir if present, else the newest numbered step), or
        None when no checkpoint exists. Resume builds its restore
        target from the on-disk layout instead of guessing layouts via
        exception handling (ADVICE r1 (a))."""
        if os.path.exists(self._latest_path):
            self._ckptr.wait_until_finished()
            meta = self._ckptr.metadata(self._latest_path)
            # orbax < 0.7 wrapped the tree (meta.item_metadata.tree);
            # 0.7 returns the metadata tree itself as a plain dict.
            # Two separate getattr steps: the fallback at each level
            # must be the value from the level above, not the original
            # wrapper, or an item_metadata-without-tree shape resolves
            # back to the wrapper and .keys() explodes
            inner = getattr(meta, "item_metadata", meta)
            tree = getattr(inner, "tree", inner)
            return set(tree.keys())
        step = self._mgr.latest_step()
        if step is None:
            return None
        meta = self._mgr.item_metadata(step)
        inner = getattr(meta, "item_metadata", meta)
        tree = getattr(inner, "tree", inner)
        return set(tree.keys())

    def best_step(self) -> Optional[int]:
        return self._mgr.best_step()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._ckptr.wait_until_finished()
        self._mgr.close()
        self._ckptr.close()


def _tuplify(tree: Any) -> Any:
    """Restore the model-init pytree structure: orbax round-trips
    tuples (the per-layer GRU stack) as lists, and an AOT executable
    (roko_tpu/compile/bundle.py) compiled against the init structure
    refuses a list-shaped pytree as a different program. Params hold
    only dicts/tuples of arrays, so list -> tuple is exact."""
    if isinstance(tree, dict):
        return {k: _tuplify(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return tuple(_tuplify(v) for v in tree)
    return tree


def load_params(path: str) -> Dict[str, Any]:
    """Load params from a checkpoint directory (best step, falling back
    to ``latest`` when no best-k step exists — e.g. a dir holding only
    the always-current ``latest``, ADVICE r1 (c)) or a single
    saved-state dir; returns the params pytree (tuple-canonical, the
    structure ``model.init`` produces — see :func:`_tuplify`)."""
    path = os.path.abspath(path)
    if os.path.isdir(path):
        entries = os.listdir(path)
        has_steps = any(name.isdigit() for name in entries)
        if has_steps or "latest" in entries:
            mgr = CheckpointManager(path)
            try:
                state = mgr.restore_best() if has_steps else None
                if state is None:
                    state = mgr.restore_latest()
            finally:
                mgr.close()
            if state is None:
                raise FileNotFoundError(f"no checkpoints under {path}")
            return _tuplify(state["params"])
    ckptr = ocp.StandardCheckpointer()
    state = ckptr.restore(path)
    return _tuplify(state["params"] if "params" in state else state)


def save_params(path: str, params: Dict[str, Any]) -> None:
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), {"params": params})
    ckptr.wait_until_finished()
