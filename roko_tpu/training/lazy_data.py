"""Out-of-core training data: stream batches from HDF5 without loading
the dataset into RAM.

The reference's lazy ``TrainDataset`` builds a flat ``idx -> (file,
group, offset)`` map and relies on torch DataLoader workers re-opening
fds (ref: roko/datasets.py:20-80). Random single-example reads are
pathological for HDF5 chunk caching, so this implementation shuffles at
two granularities instead: a seeded permutation over *chunks* of
consecutive examples per group, and an in-memory shuffle buffer of
several chunks that decorrelates neighbours before batching. Sequential
chunk reads keep HDF5 I/O streaming while the shuffle quality stays
close to a full permutation for training purposes.

Exposes the same ``batches(batch_size, rng=…, pad_to=…)`` iterator
contract as :class:`roko_tpu.training.data.InMemoryDataset`, so the
train loop treats the two interchangeably (``TrainConfig.in_memory``).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

import h5py
import numpy as np

from roko_tpu.data.hdf5 import data_group_names, hdf5_files


class StreamingDataset:
    """Lazily streams (examples, labels) from one or more HDF5 files."""

    def __init__(self, path: str, chunk_size: int = 256, buffer_chunks: int = 16):
        self.files = hdf5_files(path)
        self.chunk_size = chunk_size
        self.buffer_chunks = buffer_chunks
        #: (file_idx, group_name, start, count) per chunk
        self._chunks: List[Tuple[int, str, int, int]] = []
        self._len = 0
        for fi, filename in enumerate(self.files):
            with h5py.File(filename, "r") as fd:
                for g in data_group_names(fd):
                    n = fd[g]["examples"].shape[0]
                    if "labels" not in fd[g]:
                        raise ValueError(f"{filename}:{g} has no labels")
                    self._len += n
                    for start in range(0, n, chunk_size):
                        count = min(chunk_size, n - start)
                        self._chunks.append((fi, g, start, count))
        if not self._chunks:
            raise ValueError(f"no training groups found under {path}")

    def __len__(self) -> int:
        return self._len

    def _iter_chunks(
        self, rng: Optional[np.random.Generator]
    ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        order = np.arange(len(self._chunks))
        if rng is not None:
            rng.shuffle(order)
        fds: dict = {}
        try:
            for ci in order:
                fi, g, start, count = self._chunks[ci]
                fd = fds.get(fi)
                if fd is None:
                    fd = fds[fi] = h5py.File(self.files[fi], "r")
                x = fd[g]["examples"][start : start + count]
                y = fd[g]["labels"][start : start + count]
                yield np.asarray(x, np.uint8), np.asarray(y, np.int32)
        finally:
            for fd in fds.values():
                fd.close()

    def batches(
        self,
        batch_size: int,
        *,
        rng: Optional[np.random.Generator] = None,
        drop_remainder: bool = False,
        pad_to: Optional[int] = None,
        skip_batches: int = 0,
    ) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Same contract as InMemoryDataset.batches: yields (x, y, w).

        Delegates to the sharded input engine
        (``roko_tpu/datapipe/engine.py``) over this dataset's chunk
        table: seeded chunk permutation + per-chunk row permutations,
        read with a bounded host readahead. ``skip_batches``
        fast-forward is now O(chunks skipped) — skipped chunks are
        never read, unlike the old islice prefix re-read. The previous
        shuffle-buffer implementation survives as
        :meth:`legacy_batches` so the bench input suite can A/B the
        two readers honestly."""
        from roko_tpu.datapipe.engine import iter_span_batches

        counts = [c for (_fi, _g, _start, c) in self._chunks]
        fds: dict = {}

        def read_rows(ci: int, order: np.ndarray):
            fi, g, start, count = self._chunks[ci]
            fd = fds.get(fi)
            if fd is None:
                fd = fds[fi] = h5py.File(self.files[fi], "r")
            # same dtype contract as the legacy _iter_chunks reader
            x = np.asarray(fd[g]["examples"][start : start + count], np.uint8)
            y = np.asarray(fd[g]["labels"][start : start + count], np.int32)
            return x[order], y[order]

        def close_fds():
            for fd in fds.values():
                fd.close()
            fds.clear()

        # cleanup runs inside the engine's block generator — the same
        # thread (the prefetch producer) that does the reads, so a
        # close can never race an in-flight read
        yield from iter_span_batches(
            counts,
            read_rows,
            batch_size,
            rng=rng,
            drop_remainder=drop_remainder,
            pad_to=pad_to,
            skip_batches=skip_batches,
            prefetch=min(4, self.buffer_chunks),
            cleanup=close_fds,
        )

    def legacy_batches(
        self,
        batch_size: int,
        *,
        rng: Optional[np.random.Generator] = None,
        drop_remainder: bool = False,
        pad_to: Optional[int] = None,
        skip_batches: int = 0,
    ) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """The pre-datapipe shuffle-buffer reader, retained verbatim as
        the baseline the bench ``input`` suite measures the index layer
        against (fast-forward here really does re-read the prefix)."""
        import itertools

        yield from itertools.islice(
            self._batches_impl(
                batch_size,
                rng=rng,
                drop_remainder=drop_remainder,
                pad_to=pad_to,
            ),
            skip_batches,
            None,
        )

    def _batches_impl(
        self,
        batch_size: int,
        *,
        rng: Optional[np.random.Generator] = None,
        drop_remainder: bool = False,
        pad_to: Optional[int] = None,
    ) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        buf_x: List[np.ndarray] = []
        buf_y: List[np.ndarray] = []
        held = 0

        def drain(final: bool):
            nonlocal buf_x, buf_y, held
            x = np.concatenate(buf_x)
            y = np.concatenate(buf_y)
            if rng is not None:  # shuffle inside the buffer
                perm = rng.permutation(len(x))
                x, y = x[perm], y[perm]
            n_keep = len(x) if final else (len(x) // batch_size) * batch_size
            for s in range(0, n_keep, batch_size):
                xb = x[s : s + batch_size]
                yb = y[s : s + batch_size]
                if len(xb) < batch_size:
                    if drop_remainder:
                        break
                    if pad_to is not None:
                        pad = pad_to - len(xb)
                        w = np.concatenate(
                            [np.ones(len(xb), np.float32), np.zeros(pad, np.float32)]
                        )
                        xb = np.concatenate(
                            [xb, np.zeros((pad,) + xb.shape[1:], xb.dtype)]
                        )
                        yb = np.concatenate(
                            [yb, np.zeros((pad,) + yb.shape[1:], yb.dtype)]
                        )
                        yield xb, yb, w
                        break
                yield xb, yb, np.ones(len(xb), np.float32)
            leftovers = x[n_keep:], y[n_keep:]
            buf_x = [leftovers[0]] if len(leftovers[0]) else []
            buf_y = [leftovers[1]] if len(leftovers[1]) else []
            held = len(leftovers[0])

        for x, y in self._iter_chunks(rng):
            buf_x.append(x)
            buf_y.append(y)
            held += len(x)
            if held >= self.buffer_chunks * self.chunk_size:
                yield from drain(final=False)
        if held:
            yield from drain(final=True)
