"""Shared encoding tables and geometry constants.

This is the single source of truth for the alphabet, strand encoding and
window geometry. The reference duplicated its alphabet in two modules
(ref: roko/labels.py:6-9 vs roko/inference.py:14-17) and pinned the window
geometry in a C++ header (ref: include/generate.h:19-23); here both the
Python pipeline and the C++ extractor (roko_tpu/native) consume these
values — the native library's compiled constants are asserted against this
module at load time.
"""

from __future__ import annotations

# ---------------------------------------------------------------------------
# Alphabet / label encoding (ref: roko/labels.py:6-10)
# ---------------------------------------------------------------------------
GAP = "*"
UNKNOWN = "N"
ALPHABET = "ACGT" + GAP + UNKNOWN  # index == encoded value

ENCODING = {base: i for i, base in enumerate(ALPHABET)}
DECODING = {i: base for i, base in enumerate(ALPHABET)}

ENCODED_GAP = ENCODING[GAP]  # 4
ENCODED_UNKNOWN = ENCODING[UNKNOWN]  # 5

#: Classes predicted by the model: A, C, G, T, GAP. UNKNOWN is never a
#: target — windows containing UNKNOWN labels are rejected at feature time
#: (ref: roko/features.py:72-75).
NUM_CLASSES = 5

#: Feature values 0-5 encode a forward-strand base; reverse strand adds
#: this offset (ref: generate.cpp:17-25, 126-146).
STRAND_OFFSET = 6
FEATURE_VOCAB = 2 * len(ALPHABET)  # 12

# ---------------------------------------------------------------------------
# Window geometry (ref: include/generate.h:19-23)
# ---------------------------------------------------------------------------
#: Rows per feature window: reads sampled with replacement.
WINDOW_ROWS = 200
#: Columns per feature window: (position, insertion-slot) pairs.
WINDOW_COLS = 90
#: Windows slide by this many columns (= 60-column overlap, so every
#: position is covered by at most 3 windows).
WINDOW_STRIDE = WINDOW_COLS // 3  # 30
#: Maximum insertion slots tracked after each reference position.
MAX_INS = 3
#: Rows reserved for the draft sequence itself. The reference compiles
#: this to 0 (ref: include/generate.h:23) — kept for schema parity.
REF_ROWS = 0

# ---------------------------------------------------------------------------
# Region fan-out (ref: roko/features.py:16)
# ---------------------------------------------------------------------------
REGION_SIZE = 100_000
REGION_OVERLAP = 300

# ---------------------------------------------------------------------------
# Read filter policy (ref: include/models.h:22-23, models.cpp:25-27)
# ---------------------------------------------------------------------------
MIN_MAPQ = 10

# BAM flag bits (SAM spec §1.4).
FLAG_PAIRED = 0x1
FLAG_PROPER_PAIR = 0x2
FLAG_UNMAP = 0x4
FLAG_MUNMAP = 0x8
FLAG_REVERSE = 0x10
FLAG_MREVERSE = 0x20
FLAG_READ1 = 0x40
FLAG_READ2 = 0x80
FLAG_SECONDARY = 0x100
FLAG_QCFAIL = 0x200
FLAG_DUP = 0x400
FLAG_SUPPLEMENTARY = 0x800

#: Reads with any of these flags are excluded from the pileup.
FILTER_FLAG = (
    FLAG_UNMAP | FLAG_DUP | FLAG_QCFAIL | FLAG_SUPPLEMENTARY | FLAG_SECONDARY
)

# ---------------------------------------------------------------------------
# Base <-> feature-code helpers
# ---------------------------------------------------------------------------
#: 4-bit BAM seq nibble -> encoded base (ref: include/models.h:120-138).
#: A=1, C=2, G=4, T=8, N=15; any other nibble is an error in the reference.
NIBBLE_TO_CODE = {1: 0, 2: 1, 4: 2, 8: 3, 15: ENCODED_UNKNOWN}

#: char -> encoded base for draft sequences (ref: include/models.h:153-173).
CHAR_TO_CODE = {
    "A": 0, "a": 0,
    "C": 1, "c": 1,
    "G": 2, "g": 2,
    "T": 3, "t": 3,
    # lowercase n accepted too (soft-masked FASTAs) — the reference's
    # get_base throws on it, a latent crash we choose not to reproduce
    "N": ENCODED_UNKNOWN, "n": ENCODED_UNKNOWN, "-": ENCODED_UNKNOWN,
    "*": ENCODED_GAP,
}

# CIGAR operation codes (SAM spec §1.4.6): MIDNSHP=X
CIGAR_M, CIGAR_I, CIGAR_D, CIGAR_N, CIGAR_S, CIGAR_H, CIGAR_P, CIGAR_EQ, CIGAR_X = range(9)
CIGAR_OPS = "MIDNSHP=X"
#: ops that consume the query sequence / the reference sequence
CIGAR_CONSUMES_QUERY = (True, True, False, False, True, False, False, True, True)
CIGAR_CONSUMES_REF = (True, False, True, True, False, False, False, True, True)
