"""Mergeable latency metrics: cumulative Prometheus histograms.

The reservoir summaries in ``serve/metrics.py`` answer p50/p99 for ONE
process, but percentiles do not compose — the fleet supervisor could
only pass per-worker p99s through, never answer "what is the fleet
p99". Histograms with FIXED bucket bounds fix that by construction:
bucket counts are plain monotone counters, so fleet-level latency is
the bucket-wise SUM of the worker rows, and any scraper (Prometheus,
``tools/trace_probe.py``, the CI gate) derives quantiles from the
summed CDF. The summaries stay — exact per-worker percentiles are
still the better single-process number — and docs/OBSERVABILITY.md
documents which rows are mergeable and which are per-worker-only.

Every instance shares :data:`DEFAULT_LATENCY_BUCKETS`; merging across
processes (or across restarts of different versions) is only sound
because the bounds never vary per process.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: fixed bucket upper bounds in SECONDS, log-spaced from sub-ms host
#: overhead to the 600 s request ceiling. Chosen once, shared by every
#: histogram in the codebase — merging only works on identical bounds.
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 600.0,
)

_INF = float("inf")


def _fmt_le(le: float) -> str:
    if le == _INF:
        return "+Inf"
    return f"{le:g}"


class HistogramFamily:
    """One named histogram with an optional single label dimension
    (e.g. ``size_class``), rendered in the Prometheus text format:

    ``<name>_bucket{le="..."} N`` (cumulative), ``<name>_sum``,
    ``<name>_count`` — plus the label when set. Thread-safe; observe is
    two dict updates under a lock (the serve hot path pays ~100 ns)."""

    def __init__(
        self,
        name: str,
        *,
        label: Optional[str] = None,
        buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
    ):
        self.name = name
        self.label = label
        self.buckets: Tuple[float, ...] = tuple(buckets) + (_INF,)
        self._lock = threading.Lock()
        #: label value -> per-bucket NON-cumulative counts ("" = the
        #: unlabeled aggregate row, always kept)
        self._counts: Dict[str, List[int]] = {}
        self._sums: Dict[str, float] = {}
        self._totals: Dict[str, int] = {}

    def _row(self, key: str) -> List[int]:
        row = self._counts.get(key)
        if row is None:
            row = self._counts[key] = [0] * len(self.buckets)
            self._sums[key] = 0.0
            self._totals[key] = 0
        return row

    def observe(
        self,
        seconds: float,
        label_value: Optional[str] = None,
        extra_labels: Iterable[Tuple[str, str]] = (),
    ) -> None:
        # linear scan beats bisect at ~18 buckets and costs nothing to
        # reason about; the first bound >= value takes the count
        idx = 0
        for idx, le in enumerate(self.buckets):  # noqa: B007
            if seconds <= le:
                break
        # extra_labels adds independent single-label rows (e.g.
        # tenant="bulk", model="v2") beside the primary-label row; each
        # key is stored PRE-RENDERED as 'name="value"' so render() can
        # emit it verbatim and the fleet parse/merge stays label-blind.
        # The unlabeled aggregate still counts each observation ONCE.
        keys = [""] + ([label_value] if label_value else []) + [
            f'{ln}="{lv}"' for ln, lv in extra_labels if lv
        ]
        with self._lock:
            for key in keys:
                self._row(key)[idx] += 1
                self._sums[key] += seconds
                self._totals[key] += 1

    def cumulative(
        self, label_value: str = ""
    ) -> List[Tuple[float, int]]:
        """``[(le, cumulative_count), ...]`` for one row — the shape
        :func:`quantile_from_buckets` consumes."""
        with self._lock:
            row = self._counts.get(label_value)
            if row is None:
                return []
            out, acc = [], 0
            for le, c in zip(self.buckets, row):
                acc += c
                out.append((le, acc))
            return out

    def count(self, label_value: str = "") -> int:
        with self._lock:
            return self._totals.get(label_value, 0)

    def render(self) -> List[str]:
        """Prometheus text lines (``# TYPE`` + every row). The unlabeled
        aggregate renders first; labeled rows carry ``self.label``."""
        with self._lock:
            keys = sorted(self._counts)
            rows = {
                k: (list(self._counts[k]), self._sums[k], self._totals[k])
                for k in keys
            }
        if not rows:
            return []
        lines = [f"# TYPE {self.name} histogram"]
        for key in ([""] if "" in rows else []) + [k for k in keys if k]:
            counts, total_sum, total = rows[key]
            if not key:
                pair = ""
            elif '="' in key:
                # pre-rendered extra-label row (observe(extra_labels=))
                pair = key
            else:
                pair = f'{self.label}="{key}"' if self.label else ""
            extra = f",{pair}" if pair else ""
            acc = 0
            for le, c in zip(self.buckets, counts):
                acc += c
                lines.append(
                    f'{self.name}_bucket{{le="{_fmt_le(le)}"{extra}}} {acc}'
                )
            label = f"{{{pair}}}" if pair else ""
            lines.append(f"{self.name}_sum{label} {total_sum:.6f}")
            lines.append(f"{self.name}_count{label} {total}")
        return lines


def quantile_from_buckets(
    cumulative: Sequence[Tuple[float, int]], q: float
) -> Optional[float]:
    """The q-th quantile (0..1) from cumulative ``(le, count)`` rows —
    linear interpolation inside the landing bucket, the same estimate
    Prometheus' ``histogram_quantile`` computes. None on an empty
    histogram. Works identically on one worker's rows and on
    bucket-summed fleet rows — that invariance is the whole point."""
    if not cumulative:
        return None
    total = cumulative[-1][1]
    if total <= 0:
        return None
    rank = q * total
    prev_le, prev_count = 0.0, 0
    for le, count in cumulative:
        if count >= rank:
            if le == _INF:
                # open-ended bucket: report its lower bound (no upper
                # bound to interpolate toward)
                return prev_le
            span = count - prev_count
            frac = (rank - prev_count) / span if span else 1.0
            return prev_le + (le - prev_le) * frac
        prev_le, prev_count = le, count
    return prev_le


def parse_histogram_rows(
    text: str, name: str
) -> Dict[Tuple[Tuple[str, str], ...], float]:
    """Extract every ``<name>_bucket/_sum/_count`` row from a Prometheus
    text body as ``{((label, value), ...): number}`` — labels sorted, the
    series suffix riding as a ``("__series__", "bucket"|"sum"|"count")``
    pair. The fleet supervisor merges worker bodies with this (bucket
    rows sum because the bounds are fixed), and ``tools/trace_probe.py``
    derives fleet quantiles from the same parse."""
    out: Dict[Tuple[Tuple[str, str], ...], float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        for series in ("bucket", "sum", "count"):
            prefix = f"{name}_{series}"
            if not line.startswith(prefix):
                continue
            rest = line[len(prefix):]
            labels: List[Tuple[str, str]] = [("__series__", series)]
            if rest.startswith("{"):
                end = rest.find("}")
                if end < 0:
                    break
                body, rest = rest[1:end], rest[end + 1:]
                for pair in body.split(","):
                    if "=" not in pair:
                        continue
                    k, v = pair.split("=", 1)
                    labels.append((k.strip(), v.strip().strip('"')))
            parts = rest.split()
            if len(parts) != 1:
                break
            try:
                value = float(parts[0])
            except ValueError:
                break
            out[tuple(sorted(labels))] = value
            break
    return out


def render_histogram_rows(
    name: str,
    rows: Dict[Tuple[Tuple[str, str], ...], float],
    extra: str = "",
) -> List[str]:
    """Render parsed/merged rows back to Prometheus text: ``_bucket``
    lines grouped by their non-``le`` labels (``le`` in numeric order),
    then ``_sum``/``_count``. ``extra`` appends verbatim label text
    (e.g. ``worker="0"``) to every row — the supervisor uses it for the
    per-worker re-export beside the bucket-summed fleet rows."""

    def _le_key(le: str) -> float:
        return _INF if le == "+Inf" else float(le)

    groups: Dict[Tuple[Tuple[str, str], ...], Dict[str, float]] = {}
    scalars: Dict[Tuple[Tuple[str, str], ...], Dict[str, float]] = {}
    for key, value in rows.items():
        labels = dict(key)
        series = labels.pop("__series__", "")
        le = labels.pop("le", None)
        group = tuple(sorted(labels.items()))
        if series == "bucket" and le is not None:
            groups.setdefault(group, {})[le] = value
        elif series in ("sum", "count"):
            scalars.setdefault(group, {})[series] = value

    def _labels_text(group, le: Optional[str] = None) -> str:
        parts = ([f'le="{le}"'] if le is not None else []) + [
            f'{k}="{v}"' for k, v in group
        ]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def _num(v: float) -> str:
        return str(int(v)) if float(v).is_integer() else f"{v:.6f}"

    lines: List[str] = []
    for group in sorted(set(groups) | set(scalars)):
        for le in sorted(groups.get(group, {}), key=_le_key):
            lines.append(
                f"{name}_bucket{_labels_text(group, le)} "
                f"{_num(groups[group][le])}"
            )
        sc = scalars.get(group, {})
        if "sum" in sc:
            lines.append(f"{name}_sum{_labels_text(group)} {sc['sum']:.6f}")
        if "count" in sc:
            lines.append(
                f"{name}_count{_labels_text(group)} {_num(sc['count'])}"
            )
    return lines


def merge_histogram_rows(
    bodies: Iterable[Dict[Tuple[Tuple[str, str], ...], float]]
) -> Dict[Tuple[Tuple[str, str], ...], float]:
    """Bucket-wise sum of parsed rows from many workers — valid because
    every process uses :data:`DEFAULT_LATENCY_BUCKETS` (counters over
    identical bounds add)."""
    out: Dict[Tuple[Tuple[str, str], ...], float] = {}
    for rows in bodies:
        for key, value in rows.items():
            out[key] = out.get(key, 0.0) + value
    return out
