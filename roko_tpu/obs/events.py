"""The structured event plane: ONE emitter behind every ``ROKO_*`` line.

Five subsystems grew five independently invented stderr formats
(``ROKO_GUARD`` / ``ROKO_WATCHDOG`` / ``ROKO_FAILOVER`` /
``ROKO_ROLLOUT`` plus the supervisor's fleet prose). This module is the
single place those formats live now:

- :func:`format_line` renders the legacy grep-stable one-liner
  (``ROKO_<SUBSYSTEM> event=<event> k=v ...``) byte-compatibly — float
  compaction and key order follow the call site, exactly as
  ``training/guard.py:guard_line`` always did;
- :func:`emit` writes that line through the caller's ``log`` (stderr by
  default) AND appends one JSON record to the optional event-log sink
  (``--event-log PATH`` on the serve/polish/train CLIs,
  ``ServeConfig.event_log`` / ``GuardConfig.event_log``), so the same
  event is greppable in a terminal and queryable as data;
- the sink (:class:`EventLog`) is JSONL with size-capped rotation
  (``<path>`` -> ``<path>.1``), fsync-free append — events are
  diagnostics, not a journal; losing the tail on a power cut is fine.

A tier-1 guard test (``tests/test_obs.py``) greps the package for bare
``ROKO_*`` event literals outside ``obs/`` so a new subsystem can't
fork the format again.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, Optional

Log = Callable[[str], None]

#: event subsystems with a reserved legacy stderr prefix (``ROKO_<X>``).
#: Every one-line event format in the codebase routes through here.
SUBSYSTEMS = (
    "guard", "watchdog", "failover", "rollout", "fleet", "serve", "trace",
    "job", "store", "federation",
)


def legacy_prefix(subsystem: str) -> str:
    """The grep prefix of ``subsystem``'s legacy one-liners
    (``guard`` -> ``ROKO_GUARD``)."""
    return "ROKO_" + subsystem.upper()


def _fmt_value(v: Any) -> str:
    # the guard_line float compaction, applied plane-wide: floats render
    # %.6g so thresholds and losses stay short and grep-stable
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def format_line(
    subsystem: str,
    event: str,
    fields: Optional[Dict[str, Any]] = None,
    *,
    suffix: str = "",
    bare_event: bool = False,
    text: Optional[str] = None,
) -> str:
    """The legacy one-liner: ``ROKO_<SUB> event=<event> k=v ... <suffix>``.

    ``bare_event`` drops the ``event=`` key (the watchdog's historical
    ``ROKO_WATCHDOG hang stage=...`` shape); ``text`` replaces
    everything after the prefix verbatim (the failover prose line).
    Key order follows the fields dict (call-site order)."""
    prefix = legacy_prefix(subsystem)
    if text is not None:
        return f"{prefix} {text}"
    parts = [prefix, event if bare_event else f"event={event}"]
    for k, v in (fields or {}).items():
        parts.append(f"{k}={_fmt_value(v)}")
    if suffix:
        parts.append(suffix)
    return " ".join(parts)


class EventLog:
    """Append-only JSONL sink with size-capped rotation: when the file
    passes ``max_bytes`` it is renamed to ``<path>.1`` (replacing any
    previous rotation) and a fresh file started — bounded disk for a
    long-lived service, and at least one full cap of history retained."""

    def __init__(self, path: str, max_bytes: int = 64 * 2**20):
        self.path = path
        self.max_bytes = max(1, int(max_bytes))
        self._lock = threading.Lock()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        # append: a restarted service continues the same log
        self._f = open(path, "a", encoding="utf-8")

    def write(self, record: Dict[str, Any]) -> None:
        line = json.dumps(record, default=str, separators=(",", ":"))
        with self._lock:
            if self._f is None:
                return  # sink died (disk full / dir vanished): stay dead
            try:
                if self._f.tell() + len(line) + 1 > self.max_bytes:
                    self._rotate()
                self._f.write(line + "\n")
                self._f.flush()
            except (OSError, ValueError):
                # diagnostics must never take the service down with
                # them; ValueError = a write raced a failed rotation's
                # closed handle. Mark the sink dead rather than raising
                # out of emit() on every later event.
                if self._f is not None:
                    try:
                        self._f.close()
                    except (OSError, ValueError):
                        pass
                self._f = None

    def _rotate(self) -> None:
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            # rename failed (``.1`` is a directory, EPERM mount): KEEP
            # the existing history — the live handle stays valid and
            # the file grows past the cap (retried on the next write)
            # rather than truncating the only copy
            return
        self._f.close()
        try:
            self._f = open(self.path, "w", encoding="utf-8")
        except OSError:
            # reopen failed (dir gone, quota): dead sink, not a crash —
            # write() guards on None from here on
            self._f = None
            raise

    def close(self) -> None:
        with self._lock:
            try:
                if self._f is not None:
                    self._f.close()
            except (OSError, ValueError):
                pass
            self._f = None


#: process-global sink (None = events go to stderr/log only). One per
#: process is right: fleet workers are separate processes and the CLI
#: suffixes the path per worker id so rotation never races.
_sink: Optional[EventLog] = None


def configure_event_log(
    path: Optional[str], max_mb: float = 64.0
) -> Optional[str]:
    """Install (or, with ``path=None``, remove) the process-global JSONL
    sink. Returns the configured path. Called once at CLI start; safe to
    call again (the previous sink is closed)."""
    global _sink
    if _sink is not None:
        _sink.close()
        _sink = None
    if path:
        _sink = EventLog(path, max_bytes=int(max_mb * 2**20))
    return path


def event_log_path() -> Optional[str]:
    """The live sink's path (None when no ``--event-log`` is set)."""
    return _sink.path if _sink is not None else None


def _stderr(line: str) -> None:
    print(line, file=sys.stderr, flush=True)


def emit(
    subsystem: str,
    event: str,
    *,
    request_id: Optional[str] = None,
    log: Optional[Log] = None,
    suffix: str = "",
    bare_event: bool = False,
    text: Optional[str] = None,
    quiet: bool = False,
    **fields: Any,
) -> str:
    """Emit one event: the legacy one-liner through ``log`` (stderr by
    default) plus a JSON record to the configured sink. Returns the
    rendered line.

    ``quiet=True`` skips the line entirely (sink-only) — for
    per-request plumbing events (fleet dispatch spans) that would spam
    stderr on the hot path; without a sink configured a quiet emit is
    free."""
    sink = _sink
    if quiet and sink is None:
        return ""  # nothing would be written; skip the formatting too
    line = format_line(
        subsystem, event, fields,
        suffix=suffix, bare_event=bare_event, text=text,
    )
    if not quiet:
        (log or _stderr)(line)
    if sink is not None:
        record: Dict[str, Any] = {
            "ts": round(time.time(), 6),
            "subsystem": subsystem,
            "event": event,
        }
        if request_id is not None:
            record["request_id"] = request_id
        record.update(fields)
        if suffix:
            record["note"] = suffix
        if text is not None:
            record["text"] = text
        sink.write(record)
    return line
