"""Unified observability plane (docs/OBSERVABILITY.md).

Three legs, one package — the substrate every runtime subsystem
reports through:

- ``events``  — ONE structured emitter behind every ``ROKO_*`` stderr
  one-liner (guard, watchdog, failover, rollout, fleet, serve), with an
  optional JSONL sink (``--event-log``) under size-capped rotation. The
  legacy grep-stable line formats are preserved byte-for-byte; the JSON
  record adds ts / subsystem / event / request_id / fields.
- ``trace``   — request-scoped tracing: a ``request_id`` minted at the
  front end (or honored from ``X-Roko-Request-Id``) rides the request
  supervisor -> worker -> scheduler -> device; per-request span
  breakdowns (queue-wait, pack, device step, scatter, stitch) return in
  the reply ``timings`` field and land in a bounded in-memory ring
  served by ``GET /tracez``.
- ``hist``    — cumulative Prometheus histograms with FIXED buckets, so
  the fleet supervisor aggregates latency by bucket-sum instead of
  passing through unmergeable per-worker percentiles.
"""

from roko_tpu.obs.events import (
    configure_event_log,
    emit,
    event_log_path,
    format_line,
    legacy_prefix,
)
from roko_tpu.obs.hist import (
    DEFAULT_LATENCY_BUCKETS,
    HistogramFamily,
    parse_histogram_rows,
    quantile_from_buckets,
)
from roko_tpu.obs.trace import RequestTrace, TraceRing, new_request_id

__all__ = [
    "DEFAULT_LATENCY_BUCKETS",
    "HistogramFamily",
    "RequestTrace",
    "TraceRing",
    "configure_event_log",
    "emit",
    "event_log_path",
    "format_line",
    "legacy_prefix",
    "new_request_id",
    "parse_histogram_rows",
    "quantile_from_buckets",
]
