"""Request-scoped tracing: where did this request spend its time.

Every ``POST /polish`` gets a ``request_id`` — minted at whichever
front end sees it first (the fleet supervisor, or a worker serving
directly) or honored from an ``X-Roko-Request-Id`` header — and a
:class:`RequestTrace` that rides the request through the batching plane
collecting named spans:

- ``queue_wait`` — submit until the first window packs into a device
  step;
- ``pack``       — slab copies building each packed step;
- ``device``     — the predict dispatch itself, one span per device
  step the request's windows rode, annotated with the rung, a global
  step id, the packed occupancy, and the mesh dp width;
- ``scatter``    — predictions scattering back per segment;
- ``stitch``     — vote-board stitch in the HTTP handler.

The reply carries the breakdown as a ``timings`` field (span sums +
per-step detail), and the completed trace lands in the process-wide
:class:`TraceRing` — a bounded last-N plus a slowest-N board — served
by ``GET /tracez`` next to a live scheduler snapshot. Tracing is
always on: the cost is a few ``perf_counter`` calls and dict appends
per request, and the ring is bounded by construction.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional


def new_request_id() -> str:
    """16 hex chars — unique enough for a trace ring and an event log,
    short enough to read in one."""
    return uuid.uuid4().hex[:16]


class RequestTrace:
    """Span accounting for ONE request. Thread-safe: the HTTP handler,
    the scheduler thread, and the device dispatch all add spans."""

    __slots__ = (
        "request_id", "windows", "worker_id", "t_wall", "_t0",
        "_spans", "_steps", "total_s", "_lock", "tenant", "model",
    )

    def __init__(
        self,
        request_id: Optional[str] = None,
        *,
        windows: int = 0,
        worker_id: Optional[int] = None,
        tenant: Optional[str] = None,
        model: Optional[str] = None,
    ):
        self.request_id = request_id or new_request_id()
        self.windows = windows
        self.worker_id = worker_id
        #: multi-tenant/model-lane identity (set by the HTTP handler;
        #: None renders nothing — single-tenant traces are unchanged)
        self.tenant = tenant
        self.model = model
        self.t_wall = time.time()
        self._t0 = time.perf_counter()
        #: span name -> [seconds, count]
        self._spans: Dict[str, List[float]] = {}
        #: per-device-step annotations (rung, step id, occupancy, dp)
        self._steps: List[Dict[str, Any]] = []
        self.total_s: Optional[float] = None
        self._lock = threading.Lock()

    def add(self, name: str, seconds: float) -> None:
        with self._lock:
            acc = self._spans.get(name)
            if acc is None:
                self._spans[name] = [seconds, 1]
            else:
                acc[0] += seconds
                acc[1] += 1

    def add_step(
        self, seconds: float, *, rung: int, step: int,
        occupancy: float, dp: int, windows: int,
    ) -> None:
        """One device step this request's windows rode (a request may
        span many steps under continuous batching)."""
        self.add("device", seconds)
        with self._lock:
            if len(self._steps) < 64:  # bounded even for huge requests
                self._steps.append({
                    "step": step,
                    "rung": rung,
                    "windows": windows,
                    "occupancy": round(occupancy, 4),
                    "dp": dp,
                    "seconds": round(seconds, 6),
                })

    def finish(self) -> float:
        """Close the trace (idempotent); returns total wall seconds."""
        if self.total_s is None:
            self.total_s = time.perf_counter() - self._t0
        return self.total_s

    def spans(self) -> Dict[str, float]:
        with self._lock:
            return {k: round(v[0], 6) for k, v in self._spans.items()}

    def timings(self) -> Dict[str, Any]:
        """The reply's ``timings`` field: total, per-span seconds, and
        the device-step detail. Span seconds sum to ~the total for an
        uncontended request (the acceptance gate pins within 10%)."""
        total = self.finish()
        return {
            "request_id": self.request_id,
            "total_s": round(total, 6),
            "spans": self.spans(),
            "device_steps": list(self._steps),
        }

    def to_dict(self) -> Dict[str, Any]:
        """The /tracez record (timings plus identity)."""
        out = self.timings()
        out["windows"] = self.windows
        out["ts"] = round(self.t_wall, 3)
        if self.worker_id is not None:
            out["worker_id"] = self.worker_id
        if self.tenant is not None:
            out["tenant"] = self.tenant
        if self.model is not None:
            out["model"] = self.model
        return out


class TraceRing:
    """Bounded retention of completed traces: the last N in arrival
    order plus a slowest-N leaderboard — O(last_n + slowest_n) memory
    forever, whatever the traffic (tests pin boundedness under
    sustained load)."""

    def __init__(self, last_n: int = 256, slowest_n: int = 32):
        self.last_n = max(1, int(last_n))
        self.slowest_n = max(1, int(slowest_n))
        self._lock = threading.Lock()
        self._last: List[Dict[str, Any]] = []
        self._slowest: List[Dict[str, Any]] = []
        self._seen = 0

    def record(self, trace: RequestTrace) -> None:
        rec = trace.to_dict()
        with self._lock:
            self._seen += 1
            self._last.append(rec)
            if len(self._last) > self.last_n:
                del self._last[: len(self._last) - self.last_n]
            total = rec.get("total_s") or 0.0
            if (
                len(self._slowest) >= self.slowest_n
                and total <= (self._slowest[-1].get("total_s") or 0.0)
            ):
                return  # can't place on the full board: skip the sort
            self._slowest.append(rec)
            self._slowest.sort(key=lambda r: -(r.get("total_s") or 0.0))
            del self._slowest[self.slowest_n:]

    def snapshot(
        self, last: Optional[int] = None, slowest: Optional[int] = None
    ) -> Dict[str, Any]:
        with self._lock:
            return {
                "seen": self._seen,
                "last": list(self._last[-(last or self.last_n):]),
                "slowest": list(self._slowest[: (slowest or self.slowest_n)]),
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._last)
