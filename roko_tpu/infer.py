"""Inference + consensus stitching.

Pipeline (semantics ref: roko/inference.py:90-154, SURVEY.md §3.4): read
feature windows from HDF5, run the jitted forward + argmax on device
(batch sharded over the mesh's ``dp`` axis), merge per-window predictions
into per-(contig, position, insertion-slot) votes on the host, and stitch
each contig:

- positions sorted by (pos, ins);
- *leading* insertion-slot predictions dropped (ref :134);
- majority base per slot; GAP predictions skipped (ref :141-143);
- draft prefix ``[:first]`` / suffix ``[last_pos+1:]`` re-attached
  (ref :137-138,146-147);
- draft positions with zero pileup coverage inside the span receive no
  votes and are omitted — a documented reference behavior we reproduce
  exactly (SURVEY.md §3.4 note).

TPU-first divergences from the reference implementation (not semantics):
votes accumulate in flat per-contig uint16 arrays via ``np.add.at``
instead of nested ``defaultdict(Counter)`` — orders of magnitude faster
at genome scale — and majority ties resolve to the lowest class index
(deterministic) where ``Counter.most_common`` ties resolve to
first-inserted (window-order dependent, i.e. nondeterministic under the
reference's multiprocess feature shuffling).
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from roko_tpu import constants as C
from roko_tpu.config import RokoConfig
from roko_tpu.data.hdf5 import iter_inference_windows, load_contigs
from roko_tpu.io.fasta import write_fasta
from roko_tpu.models.model import RokoModel
from roko_tpu.parallel.mesh import (
    AXIS_DP,
    data_sharding,
    make_mesh,
    replicated_sharding,
)
from roko_tpu.training.data import prefetch_to_device
from roko_tpu.utils.profiling import StageTimer, device_trace

Params = Dict[str, Any]

_SLOTS = C.MAX_INS + 1  # ins 0..3


def make_predict_step(model: RokoModel, mesh: Mesh) -> Callable:
    """jit'd forward + argmax: uint8[B,200,90] -> int32[B,90] class ids.
    Batch and output both sharded over dp; the host fetch concatenates."""
    data = data_sharding(mesh)

    @partial(jax.jit, in_shardings=(None, data), out_shardings=data)
    def step(params, x):
        logits = model.apply(params, x, deterministic=True)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    return step


class VoteBoard:
    """Per-contig vote accumulator: uint16[contig_len * 4 slots, 5]."""

    def __init__(self, contigs: Dict[str, str]):
        self.contigs = contigs
        self._votes: Dict[str, np.ndarray] = {}

    def _board(self, contig: str) -> np.ndarray:
        b = self._votes.get(contig)
        if b is None:
            n = len(self.contigs[contig])
            # uint16: a slot gets at most one vote per covering window,
            # and window overlap (x3) times region overlap re-extraction
            # keeps counts in single digits — 40 B/draft-base, not 80
            b = self._votes[contig] = np.zeros(
                (n * _SLOTS, C.NUM_CLASSES), np.uint16
            )
        return b

    def add(
        self, contigs: List[str], positions: np.ndarray, preds: np.ndarray
    ) -> None:
        """positions int64[B,90,2] (pos, ins); preds int[B,90]."""
        for i, name in enumerate(contigs):
            board = self._board(name)
            flat = positions[i, :, 0] * _SLOTS + positions[i, :, 1]
            np.add.at(board, (flat, preds[i]), 1)

    def stitch(self, contig: str) -> str:
        """Consensus for one contig (ref: roko/inference.py:129-151)."""
        draft = self.contigs[contig]
        board = self._votes.get(contig)
        if board is None:  # no windows at all -> draft unchanged
            return draft
        covered = np.flatnonzero(board.sum(axis=1))  # sorted (pos,ins) order
        if covered.size == 0:
            return draft
        # drop leading insertion slots (ref :134; the reference would
        # IndexError if *all* entries were insertion slots — we return the
        # draft unchanged instead)
        is_base_slot = covered % _SLOTS == 0
        if not is_base_slot.any():
            return draft
        start = int(np.argmax(is_base_slot))  # first (pos, ins=0) entry
        covered = covered[start:]
        pos_of = (covered // _SLOTS)

        first_pos = int(pos_of[0])
        last_pos = int(pos_of[-1])
        bases = np.argmax(board[covered], axis=1)  # ties -> lowest class
        keep = bases != C.ENCODED_GAP
        body = np.frombuffer(C.ALPHABET[: C.NUM_CLASSES].encode(), np.uint8)[
            bases[keep]
        ].tobytes().decode()
        return draft[:first_pos] + body + draft[last_pos + 1 :]


def run_inference(
    data_path: str,
    params: Params,
    cfg: Optional[RokoConfig] = None,
    *,
    mesh: Optional[Mesh] = None,
    batch_size: int = 128,
    prefetch: int = 2,
    trace_dir: Optional[str] = None,
    log: Callable[[str], None] = print,
) -> Dict[str, str]:
    """Predict votes for every window in ``data_path`` and stitch each
    contig; returns {contig: polished_seq}. ``trace_dir`` writes a
    TensorBoard-loadable device trace of the batch loop."""
    cfg = cfg or RokoConfig()
    mesh = mesh or make_mesh(cfg.mesh)
    dp = mesh.shape[AXIS_DP]
    if batch_size % dp:
        raise ValueError(f"batch_size {batch_size} not divisible by dp={dp}")

    model = RokoModel(cfg.model)
    params = jax.device_put(params, replicated_sharding(mesh))
    predict = make_predict_step(model, mesh)
    sharding = data_sharding(mesh)

    contigs = load_contigs(data_path)
    board = VoteBoard(contigs)
    timer = StageTimer()

    def place(item):
        names, positions, x = item
        n = len(names)
        if n < batch_size:  # fixed shapes keep one compiled executable
            pad = batch_size - n
            x = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)])
        # device_put dispatches asynchronously, so timing it here would
        # read ~0 and misattribute the transfer to the predict span —
        # transfer cost shows up inside "predict+d2h"
        return names, positions, jax.device_put(x, sharding), n

    t0 = time.perf_counter()
    n_windows = 0
    with device_trace(trace_dir):
        for names, positions, x, n in prefetch_to_device(
            iter_inference_windows(data_path, batch_size), prefetch, place
        ):
            with timer("predict+d2h"):
                preds = np.asarray(jax.device_get(predict(params, x)))[:n]
            with timer("vote"):
                board.add(names, positions, preds)
            n_windows += n
    dt = time.perf_counter() - t0
    log(
        f"inference: {n_windows} windows in {dt:.1f}s "
        f"({n_windows / max(dt, 1e-9):.0f} windows/s, "
        f"{n_windows * C.WINDOW_STRIDE / max(dt, 1e-9):.0f} bases/s)"
    )

    with timer("stitch"):
        polished = {name: board.stitch(name) for name in contigs}
    timer.report(log)
    return polished


def polish_to_fasta(
    data_path: str,
    params: Params,
    out_path: str,
    cfg: Optional[RokoConfig] = None,
    **kw: Any,
) -> None:
    polished = run_inference(data_path, params, cfg, **kw)
    write_fasta(out_path, list(polished.items()))
