"""Inference + consensus stitching.

Pipeline (semantics ref: roko/inference.py:90-154, SURVEY.md §3.4): read
feature windows from HDF5, run the jitted forward + argmax on device
(batch sharded over the mesh's ``dp`` axis), merge per-window predictions
into per-(contig, position, insertion-slot) votes on the host, and stitch
each contig:

- positions sorted by (pos, ins);
- *leading* insertion-slot predictions dropped (ref :134);
- majority base per slot; GAP predictions skipped (ref :141-143);
- draft prefix ``[:first]`` / suffix ``[last_pos+1:]`` re-attached
  (ref :137-138,146-147);
- draft positions with zero pileup coverage inside the span receive no
  votes and are omitted — a documented reference behavior we reproduce
  exactly (SURVEY.md §3.4 note).

TPU-first divergences from the reference implementation (not semantics):
votes accumulate in flat per-contig uint16 arrays via ``np.add.at``
instead of nested ``defaultdict(Counter)`` — orders of magnitude faster
at genome scale — and majority ties resolve to the lowest class index
(deterministic) where ``Counter.most_common`` ties resolve to
first-inserted (window-order dependent, i.e. nondeterministic under the
reference's multiprocess feature shuffling).
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from roko_tpu import constants as C
from roko_tpu.config import RokoConfig
from roko_tpu.data.hdf5 import SlabPool, iter_inference_windows, load_contigs
from roko_tpu.io.fasta import write_fasta
from roko_tpu.models.model import RokoModel
from roko_tpu.parallel.mesh import (
    AXIS_DP,
    data_sharding,
    make_mesh,
    replicated_sharding,
)
from roko_tpu.training.data import prefetch_to_device
from roko_tpu.utils.profiling import StageTimer, device_trace

Params = Dict[str, Any]

_SLOTS = C.MAX_INS + 1  # ins 0..3


def pad_windows(x: np.ndarray, batch_size: int) -> np.ndarray:
    """Zero-pad a window batch to exactly ``batch_size`` rows so every
    dispatch reuses one compiled executable (fixed shapes). Shared by the
    batch-job loop below and the serving session's shape ladder
    (roko_tpu/serve/session.py)."""
    n = x.shape[0]
    if n == batch_size:
        return x
    if n > batch_size:
        raise ValueError(f"batch of {n} windows exceeds pad target {batch_size}")
    pad = batch_size - n
    return np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)])


def tail_rungs(
    ladder: Sequence[int], batch_size: int, dp: int
) -> Tuple[int, ...]:
    """Padded batch sizes available to a SHORT (tail or partial) batch:
    the serve ladder's rungs that fit under ``batch_size`` and divide
    the dp mesh axis, plus ``batch_size`` itself. Steady-state batches
    always dispatch at ``batch_size`` (one executable); a short batch
    pads only to the smallest rung that fits, so the final partial
    batch of a run stops paying for ``batch_size - n`` wasted rows at
    the cost of at most ``len(rungs) - 1`` extra one-off compiles."""
    rungs = {r for r in ladder if 0 < r < batch_size and r % dp == 0}
    rungs.add(batch_size)
    return tuple(sorted(rungs))


def rung_for(rungs: Sequence[int], n: int) -> int:
    """Smallest rung >= n (the top rung caps it; callers never exceed
    the top rung because it is their full batch size)."""
    for r in rungs:
        if n <= r:
            return r
    return rungs[-1]


def make_predict_step(model: RokoModel, mesh: Mesh) -> Callable:
    """jit'd forward + argmax: uint8[B,200,90] -> int32[B,90] class ids.
    Batch and output both sharded over dp; the host fetch concatenates."""
    data = data_sharding(mesh)

    @partial(jax.jit, in_shardings=(None, data), out_shardings=data)
    def step(params, x):
        logits = model.apply(params, x, deterministic=True)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    return step


def make_ragged_predict_step(model: RokoModel, mesh: Mesh) -> Callable:
    """Ragged twin of :func:`make_predict_step`: ONE top-rung executable
    whose batch axis is always the full slab, plus a scalar valid-row
    count ``n`` — rows at or beyond ``n`` are zero-masked on device, so
    the program output is bit-identical to padding the first ``n`` rows
    with zeros (``pad_windows`` pads with zeros, which is what makes the
    ragged and padded paths byte-identical by construction).

    The scheduler packs segments densely from row 0 (serve/scheduler.py
    ``RaggedBatcher``), so the per-segment length/offset vector reduces
    to the single boundary ``n = sum(lengths)``: one scalar the kernel
    masks on, not a recompile per occupancy. On the Pallas path the mask
    is what lets row blocks past ``n`` skip their serial chains; under
    XLA it is a cheap select. ``n`` rides as a traced scalar — changing
    occupancy NEVER changes the executable."""
    data = data_sharding(mesh)

    @partial(
        jax.jit, in_shardings=(None, data, None), out_shardings=data
    )
    def step(params, x, n):
        mask = jnp.arange(x.shape[0]) < n
        x = jnp.where(
            mask.reshape((-1,) + (1,) * (x.ndim - 1)), x,
            jnp.zeros((), x.dtype),
        )
        logits = model.apply(params, x, deterministic=True)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    return step


def make_cpu_predict(model: RokoModel, params_host: Params) -> Callable:
    """Host-CPU predict closure for watchdog fail-over
    (roko_tpu/resilience): same forward + argmax as
    :func:`make_predict_step` but compiled for the CPU backend on a
    single device — usable while the accelerator is presumed wedged.
    Inputs are still padded to the ladder by the caller, so the CPU
    compile set stays as bounded as the device one. Throughput is
    degraded by orders of magnitude; the point is a COMPLETED run with
    correct output, not a fast one."""
    cpu = jax.local_devices(backend="cpu")[0]

    @jax.jit
    def step(params, x):
        logits = model.apply(params, x, deterministic=True)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def predict(x: np.ndarray) -> np.ndarray:
        with jax.default_device(cpu):
            return np.asarray(step(params_host, x))

    return predict


class VoteBoard:
    """Per-contig vote accumulator.

    Two representations, switched on draft length (VERDICT r2 task #7):

    - **dense** (below ``sparse_threshold`` bases): one
      ``uint16[contig_len * 4 slots, 5]`` array — 40 B/draft-base, the
      fast path for the reference's bacterial-scale targets;
    - **sparse-insertions** (at/above the threshold): a dense
      ``uint16[contig_len, 5]`` array for the ins=0 slots every window
      votes on (uint16 keeps the dense path's overflow headroom —
      ``np.add.at`` wraps silently, and a stride-1/--region-overlap
      config can push counts into the hundreds) plus a hash map for the
      rare ins>0 slots. Memory budget: ~10 B/draft-base + ~64 B per
      *touched* insertion slot, so a 50 Mb draft polishes in ~0.5 GB of
      board instead of 2 GB, and a 3.2 Gb human-scale draft in ~32 GB
      instead of 128 GB.

    Both representations produce identical stitches (tested with a
    forced threshold).
    """

    def __init__(self, contigs: Dict[str, str], sparse_threshold: int = 2**25):
        self.contigs = contigs
        self.sparse_threshold = sparse_threshold
        self._votes: Dict[str, np.ndarray] = {}
        self._ins: Dict[str, Dict[int, np.ndarray]] = {}

    def _is_sparse(self, contig: str) -> bool:
        return len(self.contigs[contig]) >= self.sparse_threshold

    def _board(self, contig: str) -> np.ndarray:
        b = self._votes.get(contig)
        if b is None:
            n = len(self.contigs[contig])
            if self._is_sparse(contig):
                b = np.zeros((n, C.NUM_CLASSES), np.uint16)
                self._ins[contig] = {}
            else:
                # uint16: a slot gets at most one vote per covering
                # window; counts stay in single digits
                b = np.zeros((n * _SLOTS, C.NUM_CLASSES), np.uint16)
            self._votes[contig] = b
        return b

    # uint16 vote ceiling: ``np.add.at`` wraps silently at 65536, which
    # would corrupt the consensus with no symptom. Default window
    # geometry gives single-digit counts, but --window-stride 1 /
    # --region-overlap configs are user-reachable (cli.py) and can push
    # counts into the hundreds — so every accumulation is checked
    # against this limit and aborts loudly instead of wrapping
    # (VERDICT r3 weak #7). Margin of 536 >> the <=1 vote a slot can
    # receive per window row.
    SAT_LIMIT = 65_000

    # Headroom between SAT_LIMIT and the uint16 wrap. A prior check
    # guarantees every slot is < SAT_LIMIT entering a scatter, so a wrap
    # is impossible iff no single scatter adds more than this many votes
    # to one (slot, class). Well-formed feeds add <=1 per row (<=512 per
    # chunked call), but ``add`` is public — a malformed feed with
    # duplicated (pos, ins) within a row could exceed the headroom, so
    # the per-call increment is checked BEFORE the in-place uint16 add
    # (ADVICE r4).
    _WRAP_HEADROOM = 2**16 - SAT_LIMIT

    def _check_increment(self, inc_max: int, contig: str) -> None:
        if inc_max > self._WRAP_HEADROOM:
            raise RuntimeError(
                f"vote scatter on contig {contig!r} would add {inc_max} "
                f"votes to one slot in a single call (> headroom "
                f"{self._WRAP_HEADROOM}); the feed duplicates positions "
                "within window rows — refusing to risk a uint16 wrap."
            )

    def _check_saturation(self, touched_max: int, contig: str) -> None:
        if touched_max >= self.SAT_LIMIT:
            raise RuntimeError(
                f"vote board saturation on contig {contig!r}: a slot "
                f"reached {touched_max} of {2**16 - 1} possible uint16 "
                "votes. The window stride/overlap configuration packs "
                "too many windows per draft base; widen --window-stride "
                "or reduce region overlap."
            )

    # A bincount over a span wider than this (x NUM_CLASSES int64
    # counts) would allocate more than it saves; batches whose windows
    # span a wider flat-slot range scatter with np.add.at instead.
    # 2M slots = a 500 kb contiguous stretch (~80 MB temporary). With
    # iter_inference_windows feeding batches in genome order a batch
    # spans ~batch_size x stride bases (~60k slots), so this cap is a
    # safety net for exotic feeds, not the common path.
    _BINCOUNT_SPAN_CAP = 2_000_000

    def _scatter(self, board: np.ndarray, flat: np.ndarray,
                 preds: np.ndarray, contig: str) -> None:
        """Fused scatter-add of votes into ``board[flat, preds]``.

        ``np.bincount`` over the touched span beats ``np.add.at`` by
        ~20x (the r4 host-path profile measured the per-row add.at loop
        near the device rate); per-batch counts fit far inside uint16
        (<= batch_size votes per slot), and the post-add saturation
        check runs every batch, so a slot is caught crossing SAT_LIMIT
        before the +536 headroom to the uint16 wrap can be consumed."""
        lo, hi = int(flat.min()), int(flat.max()) + 1
        if hi - lo > self._BINCOUNT_SPAN_CAP:
            # exotic wide-span path: pay an O(n log n) unique to bound
            # the per-slot increment before the wrapping np.add.at
            comb = flat.astype(np.int64) * C.NUM_CLASSES + preds
            _, mult = np.unique(comb.ravel(), return_counts=True)
            self._check_increment(int(mult.max()), contig)
            np.add.at(board, (flat, preds), 1)
            self._check_saturation(int(board[flat, preds].max()), contig)
            return
        comb = (flat.astype(np.int64) - lo) * C.NUM_CLASSES + preds
        counts = np.bincount(
            comb.ravel(), minlength=(hi - lo) * C.NUM_CLASSES
        ).reshape(-1, C.NUM_CLASSES)
        self._check_increment(int(counts.max()), contig)
        region = board[lo:hi]
        region += counts.astype(np.uint16)
        self._check_saturation(int(region.max()), contig)

    def add(
        self, contigs: List[str], positions: np.ndarray, preds: np.ndarray
    ) -> None:
        """positions int64[B,90,2] (pos, ins); preds int[B,90].

        Rows are grouped by contig (genome-scale batches are almost
        always single-contig) and each group lands in one fused
        scatter-add instead of a per-row ``np.add.at`` loop."""
        groups: Dict[str, List[int]] = {}
        for i, name in enumerate(contigs):
            groups.setdefault(name, []).append(i)
        for name, rows in groups.items():
            # <=512 rows per scatter call: a slot receives at most one
            # vote per row, so per-call increments stay below the 536
            # headroom between SAT_LIMIT and the uint16 wrap — the
            # post-scatter check therefore always fires before a wrap,
            # whatever batch size the caller uses.
            for chunk in range(0, len(rows), 512):
                self._add_rows(
                    name, positions, preds, rows[chunk : chunk + 512]
                )

    def _add_rows(
        self,
        name: str,
        positions: np.ndarray,
        preds: np.ndarray,
        rows: List[int],
    ) -> None:
        board = self._board(name)
        idx = np.asarray(rows)
        pos = positions[idx]
        prd = np.asarray(preds)[idx]
        if self._is_sparse(name):
            ins_mask = pos[:, :, 1] != 0
            base = ~ins_mask
            if base.any():
                self._scatter(board, pos[:, :, 0][base], prd[base], name)
            ins_map = self._ins[name]
            flat = pos[:, :, 0][ins_mask] * _SLOTS + pos[:, :, 1][ins_mask]
            if flat.size:
                # pre-aggregate duplicate (slot, class) votes (adjacent
                # windows overlap ~cols/stride-fold, so most slots carry
                # several votes per batch): one dict visit per UNIQUE
                # pair instead of per vote
                comb = flat * C.NUM_CLASSES + prd[ins_mask]
                uniq, cnt = np.unique(comb, return_counts=True)
                for u, votes in zip(uniq.tolist(), cnt.tolist()):
                    slot, p = divmod(u, C.NUM_CLASSES)
                    counts = ins_map.get(slot)
                    if counts is None:
                        counts = ins_map[slot] = np.zeros(
                            C.NUM_CLASSES, np.uint16
                        )
                    total = int(counts[p]) + votes
                    if total >= self.SAT_LIMIT:
                        self._check_saturation(total, name)
                    counts[p] = total
        else:
            flat = pos[:, :, 0] * _SLOTS + pos[:, :, 1]
            self._scatter(board, flat.ravel(), prd.ravel(), name)

    def _covered_and_counts(self, contig: str):
        """(covered flat slot ids sorted by (pos, ins), vote counts
        [n,5]) in either representation."""
        board = self._votes[contig]
        if not self._is_sparse(contig):
            covered = np.flatnonzero(board.sum(axis=1))
            return covered, board[covered]
        base_pos = np.flatnonzero(board.sum(axis=1))
        base_slots = base_pos * _SLOTS
        ins_map = self._ins[contig]
        if ins_map:
            ins_slots = np.fromiter(ins_map.keys(), np.int64, len(ins_map))
            ins_counts = np.stack([ins_map[s] for s in ins_slots.tolist()])
            covered = np.concatenate([base_slots, ins_slots])
            counts = np.concatenate([board[base_pos], ins_counts])
            order = np.argsort(covered, kind="stable")
            return covered[order], counts[order]
        return base_slots, board[base_pos]

    def stitch(self, contig: str) -> str:
        """Consensus for one contig (ref: roko/inference.py:129-151)."""
        draft = self.contigs[contig]
        if contig not in self._votes:  # no windows at all -> draft unchanged
            return draft
        covered, counts = self._covered_and_counts(contig)
        if covered.size == 0:
            return draft
        # drop leading insertion slots (ref :134; the reference would
        # IndexError if *all* entries were insertion slots — we return the
        # draft unchanged instead)
        is_base_slot = covered % _SLOTS == 0
        if not is_base_slot.any():
            return draft
        start = int(np.argmax(is_base_slot))  # first (pos, ins=0) entry
        covered = covered[start:]
        counts = counts[start:]
        pos_of = (covered // _SLOTS)

        first_pos = int(pos_of[0])
        last_pos = int(pos_of[-1])
        bases = np.argmax(counts, axis=1)  # ties -> lowest class
        keep = bases != C.ENCODED_GAP
        body = np.frombuffer(C.ALPHABET[: C.NUM_CLASSES].encode(), np.uint8)[
            bases[keep]
        ].tobytes().decode()
        return draft[:first_pos] + body + draft[last_pos + 1 :]

    def stitch_all(self) -> Dict[str, str]:
        """Consensus for every contig this board knows. The per-request
        unit of the serving path (one board per request) and the final
        step of the batch path below share this."""
        return {name: self.stitch(name) for name in self.contigs}


def run_inference(
    data_path: str,
    params: Params,
    cfg: Optional[RokoConfig] = None,
    *,
    mesh: Optional[Mesh] = None,
    batch_size: int = 128,
    prefetch: int = 2,
    trace_dir: Optional[str] = None,
    log: Callable[[str], None] = print,
    vote_sparse_threshold: Optional[int] = None,
    cascade_stats: Optional[Dict[str, Any]] = None,
) -> Dict[str, str]:
    """Predict votes for every window in ``data_path`` and stitch each
    contig; returns {contig: polished_seq}. ``trace_dir`` writes a
    TensorBoard-loadable device trace of the batch loop.

    Multi-host pods shard the work at **contig granularity**: process p
    polishes contigs [p::process_count] on a mesh over its *local*
    devices and returns only those (votes are host-side accumulators, so
    contig ownership keeps them process-local — no cross-host vote
    reduction needed; ``polish_to_fasta`` reassembles the FASTA)."""
    from roko_tpu.parallel import distributed

    distributed.initialize()  # no-op single host (SURVEY §5.8)
    cfg = cfg or RokoConfig()
    nproc = jax.process_count()
    contig_filter = None
    contigs = load_contigs(data_path)
    if nproc > 1 and mesh is None:
        # per-process mesh over local devices only: dp absorbs them (the
        # configured dp counted the whole pod); tp/sp keep their sizes
        import dataclasses

        mesh = make_mesh(
            dataclasses.replace(cfg.mesh, dp=-1), devices=jax.local_devices()
        )
        contig_filter = set(sorted(contigs)[jax.process_index() :: nproc])
        contigs = {k: v for k, v in contigs.items() if k in contig_filter}
    mesh = mesh or make_mesh(cfg.mesh)
    dp = mesh.shape[AXIS_DP]
    if batch_size % dp:
        raise ValueError(f"batch_size {batch_size} not divisible by dp={dp}")

    # cold-start tier (roko_tpu/compile): persistent compilation cache
    # on by default, and a configured AOT bundle replaces the compile
    # for every ladder-padded batch shape (digest-checked — a mismatch
    # refuses loudly instead of polishing with the wrong program)
    from roko_tpu.compile import load_bundle, wrap_predict
    from roko_tpu.compile.cache import enable_persistent_cache

    enable_persistent_cache(cfg.compile)
    model = RokoModel(cfg.model)
    # conversion-time weight-only quantization (models/quant.py): a raw
    # f32 checkpoint loads through the int8 converter when the config
    # asks for it; already-quantized params pass through untouched
    from roko_tpu.models.quant import maybe_quantize

    params = maybe_quantize(params, model.cfg)
    params = jax.device_put(params, replicated_sharding(mesh))
    predict = make_predict_step(model, mesh)
    sharding = data_sharding(mesh)

    # vote_sparse_threshold overrides the dense/sparse board switch
    # (default 32 Mb): tests force the sparse representation through
    # the full pipeline; genome-scale callers can pin either mode
    board = (
        VoteBoard(contigs, sparse_threshold=vote_sparse_threshold)
        if vote_sparse_threshold is not None
        else VoteBoard(contigs)
    )
    timer = StageTimer()
    # every full batch dispatches at batch_size (one steady-state
    # executable); the single short TAIL batch pads only to the nearest
    # serve-ladder rung instead of all the way up to batch_size, so a
    # 1-window tail on a --b 2048 run stops paying 2047 rows of wasted
    # compute for one extra (one-off, never steady-state) compile.
    # The ladder resolves through the session's denomination rule (auto
    # default = per-device base rungs x this mesh's dp)
    from roko_tpu.config import resolve_ladder

    rungs = tail_rungs(resolve_ladder(cfg.serve, dp), batch_size, dp)
    if cfg.compile.bundle_dir:
        predict = wrap_predict(
            predict,
            load_bundle(
                cfg.compile.bundle_dir, cfg, mesh=mesh, rungs=rungs,
                log=log,
            ),
        )

    # adaptive compute (roko_tpu/cascade, docs/SERVING.md "Adaptive
    # compute"): cheap-tier + cache routing in front of the device; only
    # the escalated subset pays the reference predict. Built against the
    # post-quantize params (the exact tree tier 2 predicts with), so the
    # cache keys and calibration identity match what actually runs.
    router = None
    if cfg.cascade.enabled:
        from roko_tpu.cascade import build_router

        router = build_router(cfg, params=params)

    def tier2_predict(xe: np.ndarray) -> np.ndarray:
        n = len(xe)
        xp = jax.device_put(pad_windows(xe, rung_for(rungs, n)), sharding)
        return np.asarray(jax.device_get(predict(params, xp)))[:n]

    def place(item):
        names, positions, x, release = item
        n = len(names)
        x = pad_windows(x, rung_for(rungs, n))
        # device_put dispatches asynchronously, so timing it here would
        # read ~0 and misattribute the transfer to the predict span —
        # transfer cost shows up inside "predict+d2h"
        return names, positions, jax.device_put(x, sharding), n, release

    t0 = time.perf_counter()
    n_windows = 0
    if router is not None:
        # cascaded loop: routing decides per batch what reaches the
        # device, so batches stay host-side until after tier 1 — the
        # one-deep device pipeline below doesn't apply (escalation is
        # data-dependent). At threshold 0 every window escalates through
        # tier2_predict, the same pad/rung/predict as the plain loop:
        # output stays byte-identical (the identity gate).
        pool = SlabPool()
        for names, positions, x, release in iter_inference_windows(
            data_path, batch_size, contig_filter=contig_filter, pool=pool
        ):
            with timer("cascade"):
                preds = router.route(np.asarray(x), tier2_predict)
            with timer("vote"):
                board.add(names, positions, preds)
            release()
            n_windows += len(names)
        dt = time.perf_counter() - t0
        s = router.stats()
        if cascade_stats is not None:
            cascade_stats.update(s)
        log(
            f"inference: {n_windows} windows in {dt:.1f}s "
            f"({n_windows / max(dt, 1e-9):.0f} windows/s) — cascade "
            f"escalated {s['escalated']}/{s['windows']} "
            f"({100 * s['escalation_fraction']:.1f}%), cache hit rate "
            f"{100 * s['cache_hit_rate']:.1f}%"
        )
        with timer("stitch"):
            polished = board.stitch_all()
        timer.report(log)
        return polished
    with device_trace(trace_dir):
        # one-deep software pipeline: dispatch batch k+1's predict
        # (async under jax) BEFORE blocking on batch k's device->host
        # fetch and voting, so host-side vote accumulation overlaps
        # device compute instead of serialising with it. The
        # "predict+d2h" span therefore measures time actually BLOCKED
        # on the device, not raw step time. Slab buffers recycle
        # through a SlabPool; a batch's release runs after its vote,
        # when its position/example views are dead (the device_put
        # transfer finished before its predict results came back).
        pool = SlabPool()
        pending = None  # (names, positions, preds_future, n, release)

        def drain(entry):
            pnames, ppos, pfut, pn, prelease = entry
            with timer("predict+d2h"):
                preds = np.asarray(jax.device_get(pfut))[:pn]
            with timer("vote"):
                board.add(pnames, ppos, preds)
            prelease()
            return pn

        for names, positions, x, n, release in prefetch_to_device(
            iter_inference_windows(
                data_path, batch_size, contig_filter=contig_filter, pool=pool
            ),
            prefetch,
            place,
        ):
            fut = predict(params, x)
            if pending is not None:
                n_windows += drain(pending)
            pending = (names, positions, fut, n, release)
        if pending is not None:
            n_windows += drain(pending)
    dt = time.perf_counter() - t0
    log(
        f"inference: {n_windows} windows in {dt:.1f}s "
        f"({n_windows / max(dt, 1e-9):.0f} windows/s, "
        f"{n_windows * C.WINDOW_STRIDE / max(dt, 1e-9):.0f} bases/s)"
    )

    with timer("stitch"):
        polished = board.stitch_all()
    timer.report(log)
    return polished


def polish_to_fasta(
    data_path: str,
    params: Params,
    out_path: str,
    cfg: Optional[RokoConfig] = None,
    **kw: Any,
) -> None:
    """Polish and write FASTA. On a pod every process writes its owned
    contigs to ``out_path.part{p}`` (shared filesystem assumed, as for
    checkpoints), synchronises, and the primary merges the parts in
    draft order."""
    polished = run_inference(data_path, params, cfg, **kw)
    if jax.process_count() == 1:
        write_fasta(out_path, list(polished.items()))
        return

    from jax.experimental import multihost_utils

    part = f"{out_path}.part{jax.process_index()}"
    write_fasta(part, list(polished.items()))
    multihost_utils.sync_global_devices("roko_polish_parts_written")
    if jax.process_index() == 0:
        import os

        from roko_tpu.io.fasta import read_fasta

        merged: Dict[str, str] = {}
        for p in range(jax.process_count()):
            for name, seq in read_fasta(f"{out_path}.part{p}"):
                merged[name] = seq
        order = sorted(merged)  # contig_filter split sorted names
        write_fasta(out_path, [(n, merged[n]) for n in order])
        for p in range(jax.process_count()):
            os.remove(f"{out_path}.part{p}")
    multihost_utils.sync_global_devices("roko_polish_merged")
