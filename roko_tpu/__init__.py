"""roko_tpu — a TPU-native deep-learning consensus polisher framework.

A from-scratch reimplementation of the capabilities of lbcb-sci/roko
(reference layout documented in SURVEY.md), designed TPU-first:

- host side: self-contained BAM/BGZF I/O (no htslib dependency), a C++
  feature extractor for the pileup hot path, multiprocess region fan-out,
  HDF5 interchange;
- device side: pure JAX/Flax models (bidirectional GRU with a Pallas
  recurrent kernel, transformer-encoder variant), `jit`-compiled train and
  inference steps sharded over a `jax.sharding.Mesh` (dp/tp/sp axes) with
  XLA collectives over ICI.

Pipeline (mirrors the reference's three CLI stages, ref: README.md:7,
plus built-in evaluation the reference delegates to external pomoxis):

    roko-tpu features   FASTA + BAM [+ truth BAM]  ->  features.hdf5
    roko-tpu train      features.hdf5 dir          ->  orbax checkpoints
    roko-tpu inference  features.hdf5 + checkpoint ->  polished.fasta
    roko-tpu assess     polished + truth FASTA     ->  error rates + Qscore
    roko-tpu polish     one-shot features + inference [+ assess]
"""

__version__ = "0.1.0"

from roko_tpu import constants  # noqa: F401
from roko_tpu.config import (  # noqa: F401
    ModelConfig,
    ReadFilterConfig,
    RegionConfig,
    RokoConfig,
    TrainConfig,
    WindowConfig,
)
