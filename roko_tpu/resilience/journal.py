"""Sidecar journal that makes the streaming polish crash-resumable.

The streaming engine writes the polished FASTA incrementally; a crash
(OOM kill, preemption, SIGKILL) mid-run used to lose every finished
contig because the partial FASTA is untrustworthy (a record may be half
written). :class:`PolishJournal` keeps a durable record NEXT to the
output so ``roko-tpu polish --resume`` recomputes only what is missing:

``<out>.resume/``
    ``meta.json``       run identity (ref/bam/seed, a sha1 of the model
                        params, the window/extraction config + format
                        version) — a resume against different inputs,
                        weights or geometry is refused;
    ``<sha1>.seq``      one polished contig, written ATOMICALLY
                        (tmp file + fsync + ``os.replace``);
    ``manifest.jsonl``  one line per committed contig
                        ``{"contig", "file", "windows"}``, appended and
                        fsync'd only AFTER its ``.seq`` landed;
    ``units.jsonl``     the distributed-polish unit ledger (one event
                        per line: attempt / commit / quarantine, with
                        attempt counts and worker ids), written by the
                        ``polish --distributed`` coordinator
                        (roko_tpu/pipeline/distpolish.py);
    ``unit-<sha1>.npz`` a committed SPAN unit's raw predictions
                        (positions + preds), written atomically BEFORE
                        its ledger commit line — a resumed coordinator
                        re-stitches giant contigs from these instead of
                        re-running the units.

Commit order makes the journal crash-consistent at every byte: a torn
trailing manifest line (the crash hit mid-append) fails to parse and is
ignored; a parsed line whose ``.seq`` file is missing is ignored too.
Everything that does parse is a contig whose sequence is complete on
disk. On success the engine deletes the whole directory — the journal
exists only while a run is unfinished.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import shutil
from typing import Callable, Dict, Optional, Tuple

Log = Callable[[str], None]

_FORMAT = 1


class JournalMismatch(RuntimeError):
    """``--resume`` pointed at a journal written by a different run
    (other inputs/seed) — resuming would splice two different polishes
    into one FASTA."""


def _fsync_write(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def _journal_dir(out_path: str) -> str:
    """Where the journal lives: next to a local output; for a
    store-scheme output URL (the journal needs a real, fsync-able
    filesystem) a DETERMINISTIC local scratch dir keyed by the URL —
    the same host resuming the same remote output finds the same
    journal."""
    from roko_tpu.datapipe.io import path_scheme

    if path_scheme(out_path) in ("", "file"):
        return out_path + ".resume"
    key = hashlib.sha256(out_path.encode()).hexdigest()[:16]
    return os.path.join(
        os.path.expanduser("~"), ".cache", "roko_tpu", "journal",
        key + ".resume",
    )


class PolishJournal:
    def __init__(self, out_path: str):
        from roko_tpu.datapipe.io import path_scheme

        self.dir = _journal_dir(out_path)
        os.makedirs(os.path.dirname(self.dir) or ".", exist_ok=True)
        #: remote ``<out>.resume/`` prefix span-pred payloads mirror to
        #: (through open_output) when the output itself is remote
        self.remote_dir = (
            out_path + ".resume"
            if path_scheme(out_path) not in ("", "file") else None
        )
        self.meta_path = os.path.join(self.dir, "meta.json")
        self.manifest_path = os.path.join(self.dir, "manifest.jsonl")
        self.units_path = os.path.join(self.dir, "units.jsonl")
        self._manifest_fh = None
        self._units_fh = None

    # -- lifecycle ----------------------------------------------------------

    def open(
        self, meta: Dict, *, resume: bool, log: Optional[Log] = None
    ) -> Dict[str, Tuple[str, int]]:
        """Create or reopen the journal. Returns the committed contigs
        as ``{name: (sequence, windows)}`` — empty unless ``resume`` is
        set and a matching journal exists."""
        committed: Dict[str, Tuple[str, int]] = {}
        # JSON round-trip so the comparison against a reloaded meta.json
        # is type-stable (tuples in caller config become lists, etc.)
        meta = json.loads(json.dumps(dict(meta, format=_FORMAT)))
        if os.path.isdir(self.dir):
            if resume:
                committed = self._load(meta)
            else:
                # a fresh (non-resume) run owns the path: stale state
                # from an abandoned earlier run must not leak into it
                shutil.rmtree(self.dir)
        elif resume and log is not None:
            log(f"resume: no journal at {self.dir}; running from scratch")
        os.makedirs(self.dir, exist_ok=True)
        if not os.path.exists(self.meta_path):
            _fsync_write(
                self.meta_path,
                json.dumps(meta, sort_keys=True).encode(),
            )
        self._manifest_fh = open(self.manifest_path, "a")
        if committed and log is not None:
            windows = sum(w for _, w in committed.values())
            log(
                f"resume: skipping {len(committed)} committed contig(s) "
                f"({windows} windows) from {self.dir}"
            )
        return committed

    def _load(self, meta: Dict) -> Dict[str, Tuple[str, int]]:
        try:
            with open(self.meta_path) as fh:
                have = json.load(fh)
        except (OSError, ValueError):
            raise JournalMismatch(
                f"journal at {self.dir} has no readable meta.json; "
                "delete the directory to start over"
            ) from None
        if have != meta:
            raise JournalMismatch(
                f"journal at {self.dir} was written by a different run "
                f"({have!r} != {meta!r}); delete it or rerun without "
                "--resume"
            )
        committed: Dict[str, Tuple[str, int]] = {}
        with contextlib.suppress(OSError):
            with open(self.manifest_path) as fh:
                for line in fh:
                    try:
                        rec = json.loads(line)
                        name, fname = rec["contig"], rec["file"]
                        windows = int(rec.get("windows", 0))
                    except (ValueError, KeyError, TypeError):
                        continue  # torn trailing append — not committed
                    seq_path = os.path.join(self.dir, fname)
                    try:
                        with open(seq_path) as sfh:
                            committed[name] = (sfh.read(), windows)
                    except OSError:
                        continue  # manifest ahead of a vanished file
        return committed

    # -- commits ------------------------------------------------------------

    def commit(self, name: str, seq: str, windows: int) -> None:
        """Durably record one polished contig: atomic ``.seq`` write,
        THEN the manifest line (fsync'd) — the manifest never references
        bytes that are not fully on disk."""
        fname = hashlib.sha1(name.encode()).hexdigest() + ".seq"
        _fsync_write(os.path.join(self.dir, fname), seq.encode())
        line = json.dumps(
            {"contig": name, "file": fname, "windows": windows}
        )
        self._manifest_fh.write(line + "\n")
        self._manifest_fh.flush()
        os.fsync(self._manifest_fh.fileno())

    # -- unit ledger (distributed polish) -----------------------------------

    def unit_event(
        self, uid: str, event: str, *, durable: bool = False, **fields
    ) -> None:
        """Append one ledger event for work unit ``uid``. ``durable``
        fsyncs (commits must survive a power cut; attempt bookkeeping
        is best-effort — a torn trailing line is skipped on load)."""
        if self._units_fh is None:
            self._units_fh = open(self.units_path, "a")
        line = json.dumps(dict({"unit": uid, "event": event}, **fields))
        self._units_fh.write(line + "\n")
        self._units_fh.flush()
        if durable:
            os.fsync(self._units_fh.fileno())

    def commit_unit(
        self,
        uid: str,
        windows: int,
        *,
        positions=None,
        preds=None,
        worker=None,
    ) -> None:
        """Durably record one finished work unit. Span units carry
        their prediction payload (``positions``/``preds`` arrays,
        written as an atomic ``.npz`` BEFORE the ledger line — the
        ledger never references bytes not fully on disk) so a resumed
        coordinator re-stitches the contig without re-running them."""
        fields = {"windows": int(windows)}
        if worker is not None:
            fields["worker"] = worker
        if positions is not None:
            import numpy as np

            fname = "unit-" + hashlib.sha1(uid.encode()).hexdigest() + ".npz"
            path = os.path.join(self.dir, fname)
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:
                np.savez(fh, positions=positions, preds=preds)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
            fields["file"] = fname
            if self.remote_dir is not None:
                # remote output: the span-pred payload also uploads
                # (verified PUT through open_output) so the run's
                # artifacts live with the output object, not only in
                # this host's scratch. The mirror is supplementary — the
                # local .npz is what resume reads — so a store failure
                # here must not fail the unit commit.
                from roko_tpu.datapipe.io import open_output
                from roko_tpu.datapipe.store import StoreError
                from roko_tpu.obs import events as obs_events

                with open(path, "rb") as src:
                    data = src.read()
                try:
                    dst = open_output(self.remote_dir + "/" + fname, "wb")
                    dst.write(data)
                    dst.close()
                except (StoreError, OSError) as e:
                    obs_events.emit(
                        "journal", "unit_mirror_failed",
                        unit=uid, url=self.remote_dir + "/" + fname,
                        error=f"{type(e).__name__}: {e}"[:200],
                    )
        self.unit_event(uid, "commit", durable=True, **fields)

    def load_units(self) -> Dict[str, Dict]:
        """Fold the unit ledger into latest-state records:
        ``{uid: {"state", "attempts", "windows", "file", ...}}``.
        Torn or unparseable lines are skipped (crash-consistency rule
        shared with the contig manifest). Quarantine is informational —
        a resumed run retries quarantined units with a fresh attempt
        budget (the operator fixed something, or wants the loud failure
        again)."""
        out: Dict[str, Dict] = {}
        with contextlib.suppress(OSError):
            with open(self.units_path) as fh:
                for line in fh:
                    try:
                        rec = json.loads(line)
                        uid, event = rec["unit"], rec["event"]
                    except (ValueError, KeyError, TypeError):
                        continue  # torn trailing append
                    cur = out.setdefault(uid, {"state": "pending"})
                    if event == "attempt":
                        cur["attempts"] = int(rec.get("attempts", 0))
                    elif event == "commit":
                        cur["state"] = "committed"
                        cur["windows"] = int(rec.get("windows", 0))
                        if rec.get("file"):
                            cur["file"] = rec["file"]
                    elif event == "quarantine":
                        cur["state"] = "quarantined"
        return out

    def load_unit_preds(self, rec: Dict):
        """The committed span-unit payload referenced by a
        :meth:`load_units` record, or ``None`` when the ``.npz`` is
        missing/unreadable (the unit then simply re-runs — a vanished
        payload must degrade to recompute, never to a corrupt FASTA)."""
        fname = rec.get("file")
        if not fname:
            return None
        import zipfile

        import numpy as np

        try:
            with np.load(os.path.join(self.dir, fname)) as z:
                return z["positions"], z["preds"]
        except (OSError, ValueError, KeyError, EOFError,
                zipfile.BadZipFile):
            # np.load surfaces a truncated/corrupt .npz as BadZipFile
            # or EOFError, not just OSError/ValueError
            return None

    def close(self) -> None:
        if self._manifest_fh is not None:
            self._manifest_fh.close()
            self._manifest_fh = None
        if self._units_fh is not None:
            self._units_fh.close()
            self._units_fh = None

    def finalize(self) -> None:
        """The run completed and the FASTA is whole: the journal has
        served its purpose — remove it."""
        self.close()
        shutil.rmtree(self.dir, ignore_errors=True)
