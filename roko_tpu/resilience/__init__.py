"""Resilient-runtime primitives shared by pipeline, serve, and bench
(docs/PIPELINE.md + docs/SERVING.md "Failure handling").

Round-5 operations hit a wedge signature the stack could not survive:
the device answers the init probe, then the first XLA compile hangs
forever — a silent infinite hang that loses the whole session
(VERDICT.md). Production JAX/TPU stacks treat hang detection,
preemption-resume, and bounded retries as first-class infrastructure
(t5x arxiv 2203.17189; TPUv4 pjit training arxiv 2204.06514); this
package is that layer for roko:

- ``watchdog``  — hard deadlines around calls that can hang forever
  (device compile/predict): thread-stack dump + one-line parseable
  diagnostic + :class:`HangError`, never a silent hang;
- ``retry``     — one :class:`RetryPolicy` (attempts, exponential
  backoff + jitter, retryable classes, Retry-After floors) behind the
  features fan-out re-runs, the HTTP client, and anything else that
  re-executes pure work;
- ``breaker``   — :class:`CircuitBreaker` for the serve layer: trips
  after N consecutive device failures, half-open probing re-closes it;
- ``journal``   — :class:`PolishJournal`, the sidecar manifest that
  makes the streaming polish crash-resumable (``roko-tpu polish
  --resume``);
- ``probe``     — the subprocess jit-canary backend probe (the bench's
  former bespoke implementation, shared with ``tools/chip_probe.py``).
"""

from roko_tpu.resilience.breaker import CircuitBreaker
from roko_tpu.resilience.journal import JournalMismatch, PolishJournal
from roko_tpu.resilience.probe import probe_backend
from roko_tpu.resilience.retry import RetryPolicy
from roko_tpu.resilience.watchdog import (
    DeadlinePolicy,
    HangError,
    call_with_deadline,
    dump_thread_stacks,
)

__all__ = [
    "CircuitBreaker",
    "DeadlinePolicy",
    "HangError",
    "JournalMismatch",
    "PolishJournal",
    "RetryPolicy",
    "call_with_deadline",
    "dump_thread_stacks",
    "probe_backend",
]
