"""Device watchdog: hard deadlines around calls that can hang forever.

The r5 wedge signature (VERDICT.md): the device answers ``jax.devices()``
but the first XLA compile never returns. In-process there is no way to
interrupt that call — Python cannot kill a thread stuck in native code —
so the only survivable shape is to make the *caller* expendable: run the
hazardous call on a sacrificial daemon thread, wait with a deadline, and
when it expires dump every thread's stack, emit one machine-parseable
diagnostic line, and raise :class:`HangError` from the (still healthy)
watching thread. The stuck thread is abandoned; being a daemon it cannot
block interpreter exit. Callers then either fail over (the pipeline and
serve session fall back to a CPU predict when configured) or let the
error propagate to a loud nonzero exit — never a silent infinite hang.

The diagnostic line is grep-stable::

    ROKO_WATCHDOG hang stage=<name> deadline_s=<d> threads=<n>

followed by the full ``sys._current_frames`` stack dump, so a wedged
production run leaves enough post-mortem in its log to see exactly which
frame never returned.
"""

from __future__ import annotations

import sys
import threading
import traceback
from typing import Any, Callable, Optional

from roko_tpu.obs import events as obs_events

Log = Callable[[str], None]


class HangError(RuntimeError):
    """A watched call blew its deadline. The offending call is still
    running on an abandoned daemon thread; the device behind it must be
    presumed wedged."""

    def __init__(self, stage: str, deadline_s: float):
        super().__init__(
            f"{stage!r} still running after its {deadline_s:g}s deadline; "
            "device presumed hung (thread stacks dumped to the log)"
        )
        self.stage = stage
        self.deadline_s = deadline_s


def dump_thread_stacks(skip_current: bool = False) -> str:
    """Every live thread's stack, rendered for the log — the post-mortem
    payload behind the one-line diagnostic (``sys._current_frames`` is
    the same source ``faulthandler`` reads, but this string can go
    through a ``log`` callable instead of straight to a real fd)."""
    names = {t.ident: t for t in threading.enumerate()}
    current = threading.get_ident()
    chunks = []
    for ident, frame in sorted(sys._current_frames().items()):
        if skip_current and ident == current:
            continue
        t = names.get(ident)
        label = t.name if t is not None else "?"
        daemon = " daemon" if t is not None and t.daemon else ""
        chunks.append(
            f"--- thread {label} (ident={ident}{daemon}) ---\n"
            + "".join(traceback.format_stack(frame))
        )
    return "".join(chunks).rstrip()


def thread_stack(thread: threading.Thread) -> str:
    """One live thread's current stack (empty string when the thread is
    gone) — for "I am abandoning this stuck thread" log warnings."""
    frame = sys._current_frames().get(thread.ident)
    if frame is None:
        return ""
    return "".join(traceback.format_stack(frame)).rstrip()


def hang_diagnostic(stage: str, deadline_s: float) -> str:
    """The one-line machine-parseable hang record (``ROKO_WATCHDOG hang
    stage=... deadline_s=... threads=...`` — the historical bare-event
    shape, formatted by the shared event plane)."""
    return obs_events.format_line(
        "watchdog", "hang", {
            "stage": stage,
            "deadline_s": deadline_s,
            "threads": threading.active_count(),
        },
        bare_event=True,
    )


class DeadlinePolicy:
    """Split watchdog budgets: compile-grade vs predict-grade.

    The first watched call for a given key (one key per compiled
    executable — in practice the padded batch size) may legitimately
    include a cold XLA compile, which can take minutes where steady-state
    predicts take milliseconds; under a single budget a cold cache either
    trips the watchdog (compile masquerading as a device hang) or forces
    the predict deadline so high it stops protecting anything. This
    policy hands the FIRST call per key ``compile_deadline_s`` and every
    later call ``predict_deadline_s`` (``ResilienceConfig`` carries
    both). Thread-safe — parallel warmup probes rungs concurrently."""

    def __init__(
        self, predict_deadline_s: float, compile_deadline_s: Optional[float] = None
    ):
        self.predict_deadline_s = predict_deadline_s
        self.compile_deadline_s = (
            predict_deadline_s if compile_deadline_s is None else compile_deadline_s
        )
        self._seen: set = set()
        self._lock = threading.Lock()

    def deadline_for(self, key: Any) -> "tuple[float, bool]":
        """(budget seconds, is_first_call). Marks the key seen, so the
        compile budget is spent exactly once per key."""
        with self._lock:
            first = key not in self._seen
            self._seen.add(key)
        return (self.compile_deadline_s if first else self.predict_deadline_s), first

    def forget(self, key: Any) -> None:
        """Re-arm the compile budget for ``key``. Called when a FIRST
        dispatch fails — the failure means no compiled executable landed
        in the jit cache, so the retry (e.g. after a circuit breaker's
        half-open probe) must redo the compile and would otherwise be
        judged by the tight predict deadline, recreating the
        compile-masquerading-as-hang problem this class exists to fix."""
        with self._lock:
            self._seen.discard(key)

    def is_warm(self, key: Any) -> bool:
        with self._lock:
            return key in self._seen


def call_with_deadline(
    fn: Callable[[], Any],
    deadline_s: float,
    *,
    stage: str = "call",
    log: Optional[Log] = None,
) -> Any:
    """Run ``fn()`` under a hard deadline.

    ``deadline_s <= 0`` disables the watchdog (``fn`` runs inline on the
    calling thread — zero overhead, zero protection). Otherwise ``fn``
    runs on a sacrificial daemon thread; on expiry the diagnostic line
    plus all thread stacks go to ``log`` and :class:`HangError` raises
    in the caller. An exception raised by ``fn`` itself re-raises here
    unchanged (with its original traceback attached).
    """
    if deadline_s <= 0:
        return fn()
    log = log or (lambda m: print(m, file=sys.stderr, flush=True))
    box: dict = {}
    done = threading.Event()

    def run() -> None:
        try:
            box["value"] = fn()
        except BaseException as e:
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(
        target=run, name=f"roko-watchdog-{stage}", daemon=True
    )
    t.start()
    if not done.wait(deadline_s):
        # the one-liner goes through the event plane so a configured
        # --event-log sink records the hang as data too; the full stack
        # dump stays log-only (it is a post-mortem blob, not an event)
        obs_events.emit(
            "watchdog", "hang", log=log, bare_event=True,
            stage=stage, deadline_s=deadline_s,
            threads=threading.active_count(),
        )
        log(dump_thread_stacks(skip_current=True))
        raise HangError(stage, deadline_s)
    if "error" in box:
        raise box["error"]
    return box["value"]
