"""One retry policy for every bounded-retry site in the stack.

Before this module each subsystem grew its own loop: the features
fan-out re-ran failed region jobs with a hand-rolled ``for`` (and the
streaming pipeline inherited it), the HTTP client slept raw
``retry_after_s`` values, and the serve handlers had no policy at all.
:class:`RetryPolicy` is the one implementation: attempt budget,
exponential backoff with jitter (so a fleet of rejected clients does
not retry in lockstep), a retryable-exception allowlist, and an
optional per-failure delay *floor* for protocols that name their own
minimum wait (HTTP ``Retry-After``).
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type

OnRetry = Callable[[int, BaseException, float], None]


@dataclass(frozen=True)
class RetryPolicy:
    """``max_attempts`` TOTAL attempts (1 = no retries). Delay before
    retry k (1-based) is ``base_delay_s * multiplier**(k-1)`` capped at
    ``max_delay_s``, floored by the failure's own demanded wait when a
    ``retry_after`` extractor is given, plus up to ``jitter`` fraction
    of uniform noise. Exceptions outside ``retryable`` propagate
    immediately."""

    max_attempts: int = 3
    base_delay_s: float = 0.5
    max_delay_s: float = 30.0
    multiplier: float = 2.0
    jitter: float = 0.1
    retryable: Tuple[Type[BaseException], ...] = (Exception,)

    def delay_for(
        self,
        attempt: int,
        floor_s: float = 0.0,
        rng: Optional[random.Random] = None,
    ) -> float:
        """Backoff delay after failure number ``attempt`` (1-based)."""
        d = min(
            self.max_delay_s,
            self.base_delay_s * self.multiplier ** max(0, attempt - 1),
        )
        d = max(d, floor_s)  # a server-demanded wait is a floor, not a cap
        if self.jitter > 0:
            d += d * self.jitter * (rng or random).random()
        return d

    def call(
        self,
        fn: Callable[[], object],
        *,
        on_retry: Optional[OnRetry] = None,
        retry_after: Optional[Callable[[BaseException], Optional[float]]] = None,
        sleep: Callable[[float], None] = time.sleep,
        giveup: Optional[Callable[[BaseException], bool]] = None,
    ) -> object:
        """Run ``fn`` with this policy. ``on_retry(failures, exc,
        delay)`` fires before each retry; ``retry_after(exc)`` may
        return a protocol-demanded minimum delay for that failure.
        ``giveup(exc)`` returning True propagates that failure
        immediately even when its type is retryable — for protocol
        states where retrying is actively wrong (a draining fleet asks
        callers to PARK work, not hammer the budget against it)."""
        failures = 0
        while True:
            try:
                return fn()
            except self.retryable as e:
                if giveup is not None and giveup(e):
                    raise
                failures += 1
                if failures >= self.max_attempts:
                    raise
                floor = (retry_after(e) if retry_after else None) or 0.0
                delay = self.delay_for(failures, floor_s=floor)
                if on_retry is not None:
                    on_retry(failures, e, delay)
                if delay > 0:
                    sleep(delay)
