"""Circuit breaker for the serve layer (docs/SERVING.md).

A device that starts failing every dispatch (wedged relay, OOM loop,
sick chip) must not keep eating whole request timeouts per client:
after ``failure_threshold`` CONSECUTIVE device failures the breaker
trips open — ``/polish`` sheds load instantly with 503 + ``Retry-After``
and ``/healthz`` goes unhealthy so a load balancer stops routing here.
After ``reset_s`` the breaker goes half-open and admits exactly ONE
probe request; a success re-closes it (service restored), a failure
re-opens it for another ``reset_s``.

Only *device* failures count: request-shaped errors (a client's bad
window geometry raises ``ValueError``) say nothing about the device and
never move the breaker — classification happens at the dispatch site
(``serve/batcher.py``).
"""

from __future__ import annotations

import threading
import time
from typing import Callable

CLOSED, HALF_OPEN, OPEN = "closed", "half-open", "open"
_STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    def __init__(
        self,
        failure_threshold: int = 5,
        reset_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.reset_s = reset_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self.trip_count = 0

    # -- observation --------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._observe_locked()

    def state_code(self) -> int:
        """0 closed / 1 half-open / 2 open (the /metrics gauge)."""
        return _STATE_CODES[self.state]

    def retry_after_s(self) -> float:
        """Seconds a rejected client should wait before the breaker
        could admit it (0 when not open)."""
        with self._lock:
            if self._observe_locked() != OPEN:
                return 0.0
            return max(0.0, self.reset_s - (self._clock() - self._opened_at))

    def _observe_locked(self) -> str:
        if (
            self._state == OPEN
            and self._clock() - self._opened_at >= self.reset_s
        ):
            self._state = HALF_OPEN
            self._probe_inflight = False
        return self._state

    # -- admission ----------------------------------------------------------

    def allow(self) -> bool:
        """May a request proceed right now? In half-open this CLAIMS the
        single probe slot — a caller that then fails to enqueue the
        request must call :meth:`cancel_probe` or the slot leaks."""
        with self._lock:
            state = self._observe_locked()
            if state == CLOSED:
                return True
            if state == HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True
                return True
            return False

    def cancel_probe(self) -> None:
        """Release a probe slot claimed by :meth:`allow` when the probe
        request never reached the device (e.g. the queue was full)."""
        with self._lock:
            self._probe_inflight = False

    # -- outcomes -----------------------------------------------------------

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._probe_inflight = False
            self._state = CLOSED

    def record_failure(self) -> None:
        with self._lock:
            state = self._observe_locked()
            self._consecutive += 1
            if state == HALF_OPEN or (
                state == CLOSED and self._consecutive >= self.failure_threshold
            ):
                self._state = OPEN
                self._opened_at = self._clock()
                self._probe_inflight = False
                self._consecutive = 0
                self.trip_count += 1
