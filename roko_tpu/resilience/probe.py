"""Subprocess backend probe: device init + a tiny jit canary under a
hard deadline — the out-of-process face of the watchdog.

The in-process watchdog (``resilience/watchdog.py``) protects calls in
a process whose backend is already live. This module answers the prior
question — *is the backend safe to initialize at all?* — by paying the
init + first-compile cost in a child process. The canary matters: r5
observed ``jax.devices()`` answering while the first XLA compile blocks
forever; a devices-only probe waves callers into that tar pit.

Kill policy: on timeout the probe child first gets a grace window
(``ROKO_BENCH_PROBE_KILL_GRACE_S``, default 20 s) to finish on its
own — killing a TPU client mid-claim/compile can wedge the loopback
relay (observed rounds 2 and 3), so an imminent finisher is always
preferred. A child still stuck after the grace is SIGKILLed and
reaped: the alternative, leaving a wedged child holding the device
claim, made the SUBSEQUENT bench child hang for its whole budget too
("backend probe still hung after 300s" appearing twice per run in the
BENCH_r0x artifacts). One bounded kill beats two unbounded hangs.

Users: ``roko_tpu/benchmark.py`` (probe-then-measure orchestration) and
``tools/chip_probe.py`` (the one-line CHIP_OK/CHIP_DOWN health check) —
one deadline implementation, not two.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time
from typing import Optional, Tuple


def wait_no_kill(proc, budget_s: float) -> Optional[int]:
    """Wait up to ``budget_s`` for ``proc``; return its rc, or None on
    timeout. NEVER kills: on timeout the child is abandoned to finish
    on its own (see module docstring)."""
    deadline = time.monotonic() + budget_s
    while time.monotonic() < deadline:
        rc = proc.poll()
        if rc is not None:
            return rc
        time.sleep(2.0)
    # final poll: the child may have finished during the last sleep —
    # misclassifying that as a hang would discard a completed run
    return proc.poll()


def tail_file(path: str, n: int = 2000) -> str:
    try:
        with open(path, "r", errors="replace") as f:
            return f.read()[-n:]
    except OSError:
        return ""


def spawn_logged(cmd, budget_s: float, **popen_kw) -> Tuple[Optional[int], str]:
    """Popen ``cmd`` with stdout+stderr to a temp log, wait (never kill)
    up to ``budget_s``. Returns (rc_or_None, log_tail). The log file is
    removed unless the child was abandoned (its tail may still be
    wanted for post-mortem while it runs)."""
    with tempfile.NamedTemporaryFile(
        "w+", suffix=".log", delete=False
    ) as logf:
        proc = subprocess.Popen(
            cmd, stdout=logf, stderr=subprocess.STDOUT, **popen_kw
        )
        rc = wait_no_kill(proc, budget_s)
        out = tail_file(logf.name)
    if rc is not None:
        try:
            os.unlink(logf.name)
        except OSError:
            pass
    return rc, out


# The canary also enables the persistent compilation cache (inline —
# the child can't assume roko_tpu is importable from its cwd, so the
# ROKO_COMPILE_CACHE resolution from roko_tpu/compile/cache.py is
# mirrored here): probing a chip leaves its canary compile in the
# cache, so the probe doubles as a free cache warm.
_CANARY = (
    "print('CANARY_UP', flush=True)\n"
    "import os\n"
    "import jax\n"
    "import jax.numpy as jnp\n"
    "_d = os.environ.get('ROKO_COMPILE_CACHE')\n"
    "if _d is None:\n"
    "    _d = os.path.join('~', '.cache', 'roko-tpu', 'xla-cache')\n"
    "if _d.strip().lower() not in ('', '0', 'off', 'none', 'disable',"
    " 'disabled'):\n"
    "    _d = os.path.expanduser(_d)\n"
    "    os.makedirs(_d, exist_ok=True)\n"
    "    jax.config.update('jax_compilation_cache_dir', _d)\n"
    "    jax.config.update('jax_persistent_cache_min_compile_time_secs',"
    " 0.0)\n"
    "d = jax.devices()\n"
    "print('DEVICES_OK', d[0].platform, flush=True)\n"
    "x = jnp.ones((128, 128), jnp.bfloat16)\n"
    "y = jax.jit(lambda a, b: (a @ b).sum())(x, x)\n"
    "assert float(y) != 0.0\n"
    "print('PROBE_OK', d[0].platform, getattr(d[0], 'device_kind', '?'),"
    " flush=True)\n"
)


#: stage markers the canary prints, in order, and the per-stage
#: progress budgets (seconds): a stage that shows no new marker within
#: its budget is declared stuck and the probe abandons EARLY — seconds,
#: not the full wall budget. Budgets are generous enough for an honest
#: cold path (device claim and the first XLA compile are legitimately
#: slow) yet a wedge aborts in ~15-60s instead of the historical 300s
#: ("backend probe still hung after 300s" in the BENCH_r0x runs).
#: ``ROKO_BENCH_PROBE_STAGE_TIMEOUT`` overrides every stage budget.
PROBE_STAGES = (
    ("spawn", "CANARY_UP", 15.0),
    ("backend_init", "DEVICES_OK", 60.0),
    ("canary_compile", "PROBE_OK", 60.0),
)


def _wait_stages(proc, log_path: str, timeout_s: float):
    """Watch the canary's log for stage markers with per-stage progress
    deadlines. Returns ``(rc_or_None, stuck_stage_or_None, waited_s)``
    — rc None means abandoned (never killed; see module docstring)."""
    env_stage = os.environ.get("ROKO_BENCH_PROBE_STAGE_TIMEOUT")
    t0 = time.monotonic()
    hard_deadline = t0 + timeout_s
    stage_i = 0
    stage_t0 = t0
    while True:
        out = tail_file(log_path)
        while stage_i < len(PROBE_STAGES) and PROBE_STAGES[stage_i][1] in out:
            stage_i += 1
            stage_t0 = time.monotonic()
        rc = proc.poll()
        if rc is not None:
            return rc, None, time.monotonic() - t0
        now = time.monotonic()
        if stage_i < len(PROBE_STAGES):
            stage, _marker, budget = PROBE_STAGES[stage_i]
            budget = float(env_stage) if env_stage else budget
            if now - stage_t0 > budget:
                return None, stage, now - t0
        if now >= hard_deadline:
            stage = (
                PROBE_STAGES[stage_i][0]
                if stage_i < len(PROBE_STAGES)
                else "exit"
            )
            return None, stage, now - t0
        time.sleep(0.5)


#: stderr/stdout tail of the most recent probe child, kept for callers
#: that want the tail as a STRUCTURED field (benchmark.py puts it in
#: the ``backend_probe`` obs event) without widening the 3-tuple
#: return that ``tools/chip_probe.py`` unpacks.
_LAST_TAIL = ""


def last_probe_tail() -> str:
    return _LAST_TAIL


def _kill_after_grace(proc, log) -> bool:
    """The hard backstop for a wedged probe child: wait one more grace
    window (``ROKO_BENCH_PROBE_KILL_GRACE_S``, default 20 s; 0 = never
    kill, the historical behavior), then SIGKILL and reap. Returns True
    when the child was killed. A killed probe can never eat the wall
    budget twice in one run — the device claim dies with the child
    before the bench child spawns."""
    try:
        grace = float(
            os.environ.get("ROKO_BENCH_PROBE_KILL_GRACE_S", "20")
        )
    except ValueError:
        grace = 20.0
    if grace > 0 and wait_no_kill(proc, grace) is not None:
        return False  # finished on its own inside the grace
    if grace <= 0 or proc.poll() is not None:
        return False
    try:
        proc.kill()
        proc.wait(timeout=10.0)
    except (OSError, subprocess.TimeoutExpired) as e:
        log(f"[bench] probe child SIGKILL failed: {e!r}")
        return False
    return True


def probe_backend(timeout_s: float, log) -> Tuple[bool, str, Optional[str]]:
    """Can a fresh process initialize the JAX backend AND compile?

    Runs in a subprocess so a wedged relay hangs the probe child, not
    the caller. The child's progress is watched stage by stage (spawn ->
    backend_init -> canary_compile); a stage that stalls past its budget
    abandons the probe EARLY — callers fall back to CPU in seconds, not
    minutes — and emits a structured ``watchdog`` obs event naming the
    stuck stage. A child still stuck after a further grace window is
    SIGKILLed and reaped (see module docstring — a wedged probe must
    not hold the device claim into the bench child's budget). Returns
    ``(ok, reason, platform)`` — ``platform`` is the backend the probe
    actually saw (``"tpu"``, ``"cpu"``, ...) or None when the probe
    failed before reporting one."""
    global _LAST_TAIL
    from roko_tpu.obs import events as obs_events

    with tempfile.NamedTemporaryFile(
        "w+", suffix=".log", delete=False
    ) as logf:
        proc = subprocess.Popen(
            [sys.executable, "-c", _CANARY],
            stdout=logf, stderr=subprocess.STDOUT,
        )
        rc, stuck_stage, waited = _wait_stages(proc, logf.name, timeout_s)
        killed = False
        if rc is None:
            killed = _kill_after_grace(proc, log)
            rc = proc.poll()
            if killed:
                rc = None  # a kill rc is not a verdict on the backend
        out = tail_file(logf.name)
    _LAST_TAIL = out[-2000:]
    if rc is not None:
        try:
            os.unlink(logf.name)
        except OSError:
            pass
    if rc is None:
        obs_events.emit(
            "watchdog", "probe_stuck", log=log,
            stage=stuck_stage, waited_s=round(waited, 1),
            budget_s=timeout_s, killed=killed,
        )
        fate = (
            "probe child SIGKILLed after grace"
            if killed else "probe abandoned, not killed"
        )
        return False, (
            f"backend probe still hung after {waited:.0f}s "
            f"(stuck in stage {stuck_stage!r}; relay wedged?); "
            f"{fate}. tail: {out[-300:]}"
        ), None
    if rc != 0 or "PROBE_OK" not in out:
        return False, f"backend probe rc={rc}: {out[-400:]}", None
    ok_line = [l for l in out.strip().splitlines() if "PROBE_OK" in l][-1]
    platform = ok_line.split()[1] if len(ok_line.split()) > 1 else "unknown"
    log(f"[bench] backend probe ok: {ok_line}")
    return True, "", platform
